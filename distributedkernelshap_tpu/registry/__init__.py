from distributedkernelshap_tpu.registry.classify import (  # noqa: F401
    ENGINE_PATHS,
    PathDecision,
    classify_path,
)
from distributedkernelshap_tpu.registry.onnx_lift import (  # noqa: F401
    SUPPORTED_ONNX_OPS,
    GraphSpec,
    NodeSpec,
    UnsupportedOpError,
    lift_graph,
    lift_onnx,
)
from distributedkernelshap_tpu.registry.registry import (  # noqa: F401
    ModelRegistry,
    RegisteredModel,
    TenantQuota,
)
