"""ONNX ingest: translate a supported op subset into a JAX predictor.

The format gateway's customer-facing door (ROADMAP item 4; grounded in
ONNXExplainer's format-generic Shapley framework, PAPERS.md arXiv
2309.16916): a customer hands the fleet an ONNX graph, the registry turns
it into a :class:`~distributedkernelshap_tpu.models.predictors.
BasePredictor` and classifies it onto the right engine path — a
logistic-regression export lands on the linear fast path, an MLP export on
the native masked-EY path, with no customer-side code.

Two layers, deliberately separated:

* :class:`GraphSpec` — a framework-free description of a feed-forward
  graph (nodes, initializers, one input, one output).  The translator
  (:func:`lift_graph`) and its parity tests need only this, so the
  translation core is fully exercised on environments without the
  ``onnx`` package (the minimal CI image).
* :func:`lift_onnx` — parse an ONNX ``ModelProto`` / bytes / file path
  into a :class:`GraphSpec` and lift it.  ``onnx`` is imported lazily;
  environments without it get a clear ``ImportError`` naming the
  ``requirements_advanced.txt`` pin, and everything else in the registry
  keeps working.

Supported ops (:data:`SUPPORTED_ONNX_OPS`): ``Gemm``, ``MatMul``,
``Add``, ``Relu``, ``Sigmoid``, ``Tanh``, ``Softmax``, ``Identity``,
``Reshape``, ``Flatten``.  Anything else raises a typed
:class:`UnsupportedOpError` listing EVERY unsupported op in the graph
(one round trip to learn the full gap, not one per op).

Linear extraction: a graph whose compute is purely affine
(Gemm/MatMul/Add/Identity) with at most one trailing ``Sigmoid`` /
``Softmax`` head is lowered to a native
:class:`~distributedkernelshap_tpu.models.predictors.LinearPredictor` —
``W``/``b`` are recovered exactly by probing the affine part with the
identity basis — so ONNX linear models inherit the whole linear fast
path: plan-constant device cache, masked-EY einsums, ``classify_path ==
"linear"``.
"""

import logging
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

SUPPORTED_ONNX_OPS = ("Gemm", "MatMul", "Add", "Relu", "Sigmoid", "Tanh",
                      "Softmax", "Identity", "Reshape", "Flatten")

#: ops that keep a row-wise affine function affine (the linear-extraction
#: closure); a trailing Sigmoid/Softmax on top still maps onto a
#: LinearPredictor activation
_AFFINE_OPS = frozenset({"Gemm", "MatMul", "Add", "Identity"})
_LINEAR_HEADS = {"Sigmoid": "sigmoid", "Softmax": "softmax"}


class UnsupportedOpError(ValueError):
    """The graph uses ops outside the supported subset.  ``ops`` lists
    every offending op type (sorted, deduplicated) so the caller learns
    the full translation gap from one error."""

    def __init__(self, ops: Sequence[str]):
        self.ops = sorted(set(ops))
        super().__init__(
            f"ONNX graph uses unsupported op(s) {self.ops}; this "
            f"translator speaks {list(SUPPORTED_ONNX_OPS)}")


class NodeSpec(NamedTuple):
    op: str
    inputs: tuple
    outputs: tuple
    attrs: dict


class GraphSpec(NamedTuple):
    """Framework-free feed-forward graph: topologically ordered ``nodes``
    over ``initializers`` (weights) and ONE dynamic ``input_name`` of
    width ``input_dim``, producing ``output_name``."""

    nodes: List[NodeSpec]
    initializers: Dict[str, np.ndarray]
    input_name: str
    output_name: str
    input_dim: int


def _check_ops(spec: GraphSpec) -> None:
    bad = [n.op for n in spec.nodes if n.op not in SUPPORTED_ONNX_OPS]
    if bad:
        raise UnsupportedOpError(bad)


def _eval_node(xp, node: NodeSpec, values: dict):
    """Evaluate one node with array module ``xp`` (numpy or jax.numpy);
    the single op-semantics implementation shared by the device callable,
    the linear-extraction probe and the output-shape probe."""

    op, attrs = node.op, node.attrs
    args = [values[name] for name in node.inputs]
    if op == "Gemm":
        a = args[0].T if attrs.get("transA", 0) else args[0]
        b = args[1].T if attrs.get("transB", 0) else args[1]
        y = float(attrs.get("alpha", 1.0)) * (a @ b)
        if len(args) > 2:
            y = y + float(attrs.get("beta", 1.0)) * args[2]
        return y
    if op == "MatMul":
        return args[0] @ args[1]
    if op == "Add":
        return args[0] + args[1]
    if op == "Relu":
        return xp.maximum(args[0], 0)
    if op == "Sigmoid":
        return 1.0 / (1.0 + xp.exp(-args[0]))
    if op == "Tanh":
        return xp.tanh(args[0])
    if op == "Softmax":
        axis = int(attrs.get("axis", -1))
        z = args[0] - xp.max(args[0], axis=axis, keepdims=True)
        e = xp.exp(z)
        return e / xp.sum(e, axis=axis, keepdims=True)
    if op == "Identity":
        return args[0]
    if op == "Reshape":
        data, shape = args[0], np.asarray(args[1]).astype(np.int64)
        # ONNX semantics: 0 copies the input dim (allowzero=0), -1 infers
        resolved = [int(data.shape[i]) if int(d) == 0 else int(d)
                    for i, d in enumerate(shape)]
        return xp.reshape(data, tuple(resolved))
    if op == "Flatten":
        axis = int(attrs.get("axis", 1))
        lead = int(np.prod(data_shape(args[0])[:axis])) if axis else 1
        return xp.reshape(args[0], (lead, -1))
    raise UnsupportedOpError([op])  # unreachable after _check_ops


def data_shape(arr) -> tuple:
    return tuple(int(d) for d in arr.shape)


def _run_graph(xp, spec: GraphSpec, X):
    values = {name: xp.asarray(arr)
              for name, arr in spec.initializers.items()}
    values[spec.input_name] = X
    for node in spec.nodes:
        out = _eval_node(xp, node, values)
        for name in node.outputs:
            values[name] = out
    return values[spec.output_name]


def run_graph_reference(spec: GraphSpec, X: np.ndarray) -> np.ndarray:
    """Numpy reference evaluation of the graph — the parity-test oracle
    (and the linear-extraction probe's engine)."""

    return np.asarray(_run_graph(np, spec, np.asarray(X, np.float32)),
                      dtype=np.float32)


def _try_linear(spec: GraphSpec):
    """Lower an affine(+head) graph to ``LinearPredictor`` — or ``None``.

    The affine part is recovered EXACTLY by probing with the identity
    basis: for row-wise affine ``f``, ``b = f(0)`` and ``W = f(I) - b``
    (float32 arithmetic on the same values the graph itself would
    compute, so the lowered model is bit-faithful for Gemm/MatMul/Add
    chains)."""

    ops = [n.op for n in spec.nodes]
    head = None
    if ops and ops[-1] in _LINEAR_HEADS:
        head = _LINEAR_HEADS[ops[-1]]
        body = spec.nodes[:-1]
    else:
        body = spec.nodes
    if not body or not all(n.op in _AFFINE_OPS for n in body):
        return None
    pre = GraphSpec(list(body), spec.initializers, spec.input_name,
                    body[-1].outputs[0], spec.input_dim)
    D = spec.input_dim
    try:
        b = run_graph_reference(pre, np.zeros((1, D), np.float32))
        WI = run_graph_reference(pre, np.eye(D, dtype=np.float32))
    except Exception:
        return None  # shape-incompatible probe: not a row-wise affine map
    if b.ndim != 2 or b.shape[0] != 1 or WI.shape != (D, b.shape[1]):
        return None
    from distributedkernelshap_tpu.models.predictors import LinearPredictor

    W = WI - b  # (D, K)
    # faithfulness probe: a Gemm with transA (or any other batch-coupling
    # oddity) is NOT row-wise affine even though its ops are in the affine
    # set — verify the extraction reproduces the graph before trusting it
    rng = np.random.default_rng(0)
    probe = rng.normal(size=(5, D)).astype(np.float32)
    try:
        want = run_graph_reference(pre, probe)
    except Exception:
        return None
    if want.shape != (5, W.shape[1]) \
            or not np.allclose(probe @ W + b[0], want, atol=1e-4):
        return None
    activation = head or "identity"
    if activation == "sigmoid" and W.shape[1] == 1:
        # binary logistic regression: a single sigmoid logit IS
        # softmax([0, z]) — lift to the two-column softmax form the
        # sklearn predict_proba lift uses, so downstream consumers see
        # [P(0), P(1)] and the linear fast path gets a 2-class head
        W2 = np.concatenate([np.zeros_like(W), W], axis=1)
        b2 = np.concatenate([np.zeros_like(b[0]), b[0]])
        return LinearPredictor(W2, b2, activation="softmax")
    return LinearPredictor(W, b[0], activation=activation,
                           vector_out=W.shape[1] > 1)


class ONNXPredictor:
    """Generic lifted ONNX graph: a jittable ``(n, D) -> (n, K)``
    callable over the graph's initializers (kept on-device as jnp
    constants).  Built only for graphs the linear lowering declines —
    MLPs and friends — and classified onto the sampled masked-EY path."""

    vector_out = True
    supports_masked_ey = False

    def __init__(self, spec: GraphSpec):
        import jax.numpy as jnp

        self.spec = spec
        self._jnp = jnp
        self._consts = {name: jnp.asarray(arr, jnp.float32)
                        for name, arr in spec.initializers.items()}
        probe = run_graph_reference(spec,
                                    np.zeros((2, spec.input_dim), np.float32))
        self.n_outputs = int(probe.shape[1]) if probe.ndim > 1 else 1
        self.vector_out = probe.ndim > 1

    def __call__(self, X):
        values = dict(self._consts)
        values[self.spec.input_name] = X
        for node in self.spec.nodes:
            out = _eval_node(self._jnp, node, values)
            for name in node.outputs:
                values[name] = out
        out = values[self.spec.output_name]
        return out[:, None] if out.ndim == 1 else out

    def host_fn(self, X: np.ndarray) -> np.ndarray:
        out = run_graph_reference(self.spec, X)
        return out[:, None] if out.ndim == 1 else out


def lift_graph(spec: GraphSpec):
    """Translate a :class:`GraphSpec` into a predictor: a native
    ``LinearPredictor`` when the graph is affine(+head) — the linear fast
    path — else a jittable :class:`ONNXPredictor`.  Raises
    :class:`UnsupportedOpError` listing every op outside the subset."""

    _check_ops(spec)
    linear = _try_linear(spec)
    if linear is not None:
        logger.info("ONNX graph lowered to a native LinearPredictor "
                    "(D=%d, K=%d, %s) — linear fast path", spec.input_dim,
                    linear.n_outputs, linear.activation)
        return linear
    pred = ONNXPredictor(spec)
    logger.info("ONNX graph lifted to a jittable predictor "
                "(%d nodes, D=%d, K=%d)", len(spec.nodes), spec.input_dim,
                pred.n_outputs)
    return pred


# --------------------------------------------------------------------- #
# ONNX ModelProto -> GraphSpec (the optional-import half)
# --------------------------------------------------------------------- #


def _require_onnx():
    try:
        import onnx  # noqa: F401

        return onnx
    except ImportError as e:
        raise ImportError(
            "ONNX ingest needs the optional 'onnx' package "
            "(requirements_advanced.txt); the rest of the registry works "
            "without it") from e


def graph_spec_from_onnx(model) -> GraphSpec:
    """Decode an ONNX ``ModelProto`` into a :class:`GraphSpec`."""

    onnx = _require_onnx()
    from onnx import numpy_helper

    graph = model.graph
    initializers = {init.name: np.asarray(numpy_helper.to_array(init))
                    for init in graph.initializer}
    dynamic_inputs = [i for i in graph.input
                      if i.name not in initializers]
    if len(dynamic_inputs) != 1:
        raise ValueError(
            f"expected exactly one dynamic graph input, got "
            f"{[i.name for i in dynamic_inputs]}")
    if len(graph.output) != 1:
        raise ValueError(
            f"expected exactly one graph output, got "
            f"{[o.name for o in graph.output]}")
    inp = dynamic_inputs[0]
    dims = inp.type.tensor_type.shape.dim
    if len(dims) != 2 or not dims[1].dim_value:
        raise ValueError(
            "expected a (batch, features) input with a static feature "
            "dim; got "
            + str([d.dim_value or d.dim_param for d in dims]))
    nodes = []
    for node in graph.node:
        attrs = {a.name: onnx.helper.get_attribute_value(a)
                 for a in node.attribute}
        nodes.append(NodeSpec(node.op_type, tuple(node.input),
                              tuple(node.output), attrs))
    return GraphSpec(nodes, initializers, inp.name, graph.output[0].name,
                     int(dims[1].dim_value))


def lift_onnx(source):
    """Lift an ONNX model — a ``ModelProto``, serialized ``bytes``, or a
    file path — into a predictor (see :func:`lift_graph`)."""

    onnx = _require_onnx()
    if isinstance(source, (bytes, bytearray)):
        model = onnx.load_model_from_string(bytes(source))
    elif isinstance(source, str):
        model = onnx.load(source)
    else:
        model = source
    return lift_graph(graph_spec_from_onnx(model))
