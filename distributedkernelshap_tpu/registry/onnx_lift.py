"""ONNX ingest: translate a supported op subset into a JAX predictor.

The format gateway's customer-facing door (ROADMAP item 4; grounded in
ONNXExplainer's format-generic Shapley framework, PAPERS.md arXiv
2309.16916): a customer hands the fleet an ONNX graph, the registry turns
it into a :class:`~distributedkernelshap_tpu.models.predictors.
BasePredictor` and classifies it onto the right engine path — a
logistic-regression export lands on the linear fast path, an MLP export on
the native masked-EY path, with no customer-side code.

Two layers, deliberately separated:

* :class:`GraphSpec` — a framework-free description of a feed-forward
  graph (nodes, initializers, one input, one output).  The translator
  (:func:`lift_graph`) and its parity tests need only this, so the
  translation core is fully exercised on environments without the
  ``onnx`` package (the minimal CI image).
* :func:`lift_onnx` — parse an ONNX ``ModelProto`` / bytes / file path
  into a :class:`GraphSpec` and lift it.  ``onnx`` is imported lazily;
  environments without it get a clear ``ImportError`` naming the
  ``requirements_advanced.txt`` pin, and everything else in the registry
  keeps working.

Supported ops (:data:`SUPPORTED_ONNX_OPS`): ``Gemm``, ``MatMul``,
``Add``, ``Relu``, ``Sigmoid``, ``Tanh``, ``Softmax``, ``Identity``,
``Reshape``, ``Flatten``, ``Transpose``, and — since the deep-model
attribution engine landed — the CNN block ops ``Conv``, ``MaxPool``,
``AveragePool`` and ``BatchNormalization`` (inference mode, i.e. the
folded affine transform).  Anything else raises a typed
:class:`UnsupportedOpError` listing EVERY unsupported op in the graph
with its node name and position (one round trip to learn the full gap,
not one per op — and a multi-Conv graph's offending node is locatable
from the message alone).

Convolutional graphs follow ONNX layout conventions: ``NCHW`` data,
``OIHW`` conv weights, with a leading ``Reshape``/``Transpose`` pair
lifting the engine's flattened ``(batch, features)`` rows into image
form.  These graphs are NOT lowered to the linear fast path (a Relu
between affine ops breaks row-wise affinity); they lift to an
:class:`ONNXPredictor`, which the registry classifier then promotes to
the DeepSHAP backprop path (``attribution/deepshap.py``) when every
node is rule-covered.

Linear extraction: a graph whose compute is purely affine
(Gemm/MatMul/Add/Identity) with at most one trailing ``Sigmoid`` /
``Softmax`` head is lowered to a native
:class:`~distributedkernelshap_tpu.models.predictors.LinearPredictor` —
``W``/``b`` are recovered exactly by probing the affine part with the
identity basis — so ONNX linear models inherit the whole linear fast
path: plan-constant device cache, masked-EY einsums, ``classify_path ==
"linear"``.
"""

import logging
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from distributedkernelshap_tpu.models.predictors import (
    BasePredictor as _BasePredictor,
)

logger = logging.getLogger(__name__)

SUPPORTED_ONNX_OPS = ("Gemm", "MatMul", "Add", "Relu", "Sigmoid", "Tanh",
                      "Softmax", "Identity", "Reshape", "Flatten",
                      "Transpose", "Conv", "MaxPool", "AveragePool",
                      "BatchNormalization")

#: ops that keep a row-wise affine function affine (the linear-extraction
#: closure); a trailing Sigmoid/Softmax on top still maps onto a
#: LinearPredictor activation
_AFFINE_OPS = frozenset({"Gemm", "MatMul", "Add", "Identity"})
_LINEAR_HEADS = {"Sigmoid": "sigmoid", "Softmax": "softmax"}


class UnsupportedOpError(ValueError):
    """The graph uses ops outside the supported subset.  ``ops`` lists
    every offending op type (sorted, deduplicated) and ``sites`` every
    offending node as ``"Op (node 'name', #position)"`` so the caller
    learns the full translation gap — and WHERE it sits in a multi-node
    graph — from one error."""

    def __init__(self, ops: Sequence[str],
                 sites: Optional[Sequence[str]] = None):
        self.ops = sorted(set(ops))
        self.sites = list(sites) if sites is not None else list(self.ops)
        super().__init__(
            f"ONNX graph uses unsupported op(s) {self.sites}; this "
            f"translator speaks {list(SUPPORTED_ONNX_OPS)}")


class NodeSpec(NamedTuple):
    op: str
    inputs: tuple
    outputs: tuple
    attrs: dict
    #: the ONNX node name (optional in the format; empty for hand-built
    #: specs) — carried so errors can point AT the node, not just its type
    name: str = ""


class GraphSpec(NamedTuple):
    """Framework-free feed-forward graph: topologically ordered ``nodes``
    over ``initializers`` (weights) and ONE dynamic ``input_name`` of
    width ``input_dim``, producing ``output_name``."""

    nodes: List[NodeSpec]
    initializers: Dict[str, np.ndarray]
    input_name: str
    output_name: str
    input_dim: int


def node_site(node: NodeSpec, position: Optional[int] = None) -> str:
    """``"Op (node 'name'[, #position])"`` — how errors locate a node.
    A nameless node (names are optional in ONNX) is identified by its
    first output, which IS unique in a well-formed graph; the position
    segment is omitted when the caller does not know it (eval-time
    rejections see one node, not the whole graph)."""

    label = node.name or (node.outputs[0] if node.outputs else "?")
    pos = f", #{position}" if position is not None else ""
    return f"{node.op} (node {label!r}{pos})"


def _check_ops(spec: GraphSpec) -> None:
    bad = [(n.op, node_site(n, i)) for i, n in enumerate(spec.nodes)
           if n.op not in SUPPORTED_ONNX_OPS]
    if bad:
        raise UnsupportedOpError([op for op, _ in bad],
                                 sites=[site for _, site in bad])


def _attr_ints(attrs: dict, key: str, default) -> tuple:
    value = attrs.get(key, default)
    return tuple(int(v) for v in value)


def _attr_str(attrs: dict, key: str, default: str) -> str:
    value = attrs.get(key, default)
    return value.decode() if isinstance(value, (bytes, bytearray)) \
        else str(value)


def conv_pads(node: NodeSpec) -> Tuple[tuple, tuple]:
    """Resolve a Conv/pool node's explicit spatial padding to
    ``((top, bottom), (left, right))``.  Only ``auto_pad=NOTSET`` (the
    ONNX default, explicit ``pads``) is spoken — exporters that emit
    SAME_*/VALID auto_pad get a located error instead of silently wrong
    geometry."""

    if _attr_str(node.attrs, "auto_pad", "NOTSET") != "NOTSET":
        raise ValueError(
            f"{node.op} auto_pad is not supported (export with explicit "
            f"pads): {node_site(node)}")
    pads = _attr_ints(node.attrs, "pads", (0, 0, 0, 0))
    if len(pads) != 4:
        raise ValueError(
            f"{node.op} expects 2 spatial dims (pads of length 4, got "
            f"{list(pads)}): {node_site(node)}")
    # ONNX order: [top, left, bottom, right]
    return (pads[0], pads[2]), (pads[1], pads[3])


def _np_conv(X, W, bias, strides, pads, dilations, group):
    """Reference NCHW/OIHW convolution in plain numpy: strided-slice
    accumulation over kernel taps (exact, loop count = kH*kW — the parity
    oracle for the jax route, not a performance path)."""

    N, C, H, Wd = X.shape
    O, Cg, kH, kW = W.shape
    sh, sw = strides
    dh, dw = dilations
    Xp = np.pad(X, ((0, 0), (0, 0), pads[0], pads[1]))
    Hp, Wp = Xp.shape[2], Xp.shape[3]
    Ho = (Hp - ((kH - 1) * dh + 1)) // sh + 1
    Wo = (Wp - ((kW - 1) * dw + 1)) // sw + 1
    Og = O // group
    out = np.zeros((N, O, Ho, Wo), dtype=np.float32)
    for g in range(group):
        Xg = Xp[:, g * Cg:(g + 1) * Cg]
        Wg = W[g * Og:(g + 1) * Og]
        for i in range(kH):
            for j in range(kW):
                patch = Xg[:, :, i * dh:i * dh + (Ho - 1) * sh + 1:sh,
                           j * dw:j * dw + (Wo - 1) * sw + 1:sw]
                out[:, g * Og:(g + 1) * Og] += np.einsum(
                    "nchw,oc->nohw", patch, Wg[:, :, i, j])
    if bias is not None:
        out += np.asarray(bias).reshape(1, -1, 1, 1)
    return out.astype(np.float32)


def _np_pool(X, kernel, strides, reduce_fn):
    """Reference 2-D windowed pooling (zero pads only — enforced by the
    caller): loops output positions, fine at oracle scale."""

    N, C, H, W = X.shape
    kh, kw = kernel
    sh, sw = strides
    Ho = (H - kh) // sh + 1
    Wo = (W - kw) // sw + 1
    out = np.empty((N, C, Ho, Wo), dtype=np.float32)
    for i in range(Ho):
        for j in range(Wo):
            win = X[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = reduce_fn(win, axis=(2, 3))
    return out


def _pool_geometry(node: NodeSpec):
    """``(kernel, strides)`` for a MaxPool/AveragePool node; rejects the
    attribute corners (pads, dilation, ceil rounding) whose semantics the
    attribution rules do not model, with the node located in the error."""

    kernel = _attr_ints(node.attrs, "kernel_shape", ())
    if len(kernel) != 2:
        raise ValueError(f"{node.op} expects a 2-D kernel_shape: "
                         f"{node_site(node)}")
    strides = _attr_ints(node.attrs, "strides", kernel)
    pads = conv_pads(node)
    if any(p for pair in pads for p in pair) \
            or _attr_ints(node.attrs, "dilations", (1, 1)) != (1, 1) \
            or int(node.attrs.get("ceil_mode", 0)):
        raise ValueError(
            f"{node.op} supports only unpadded, undilated, floor-mode "
            f"windows: {node_site(node)}")
    return kernel, strides


def _eval_node(xp, node: NodeSpec, values: dict):
    """Evaluate one node with array module ``xp`` (numpy or jax.numpy);
    the single op-semantics implementation shared by the device callable,
    the linear-extraction probe, the output-shape probe and the DeepSHAP
    attribution engine's forward/VJP passes."""

    op, attrs = node.op, node.attrs
    args = [values[name] for name in node.inputs]
    if op == "Gemm":
        a = args[0].T if attrs.get("transA", 0) else args[0]
        b = args[1].T if attrs.get("transB", 0) else args[1]
        y = float(attrs.get("alpha", 1.0)) * (a @ b)
        if len(args) > 2:
            y = y + float(attrs.get("beta", 1.0)) * args[2]
        return y
    if op == "MatMul":
        return args[0] @ args[1]
    if op == "Add":
        return args[0] + args[1]
    if op == "Relu":
        return xp.maximum(args[0], 0)
    if op == "Sigmoid":
        return 1.0 / (1.0 + xp.exp(-args[0]))
    if op == "Tanh":
        return xp.tanh(args[0])
    if op == "Softmax":
        axis = int(attrs.get("axis", -1))
        z = args[0] - xp.max(args[0], axis=axis, keepdims=True)
        e = xp.exp(z)
        return e / xp.sum(e, axis=axis, keepdims=True)
    if op == "Identity":
        return args[0]
    if op == "Reshape":
        data, shape = args[0], np.asarray(args[1]).astype(np.int64)
        # ONNX semantics: 0 copies the input dim (allowzero=0), -1 infers
        resolved = [int(data.shape[i]) if int(d) == 0 else int(d)
                    for i, d in enumerate(shape)]
        return xp.reshape(data, tuple(resolved))
    if op == "Flatten":
        axis = int(attrs.get("axis", 1))
        lead = int(np.prod(data_shape(args[0])[:axis])) if axis else 1
        return xp.reshape(args[0], (lead, -1))
    if op == "Transpose":
        perm = _attr_ints(attrs, "perm",
                          tuple(reversed(range(args[0].ndim))))
        return xp.transpose(args[0], perm)
    if op == "Conv":
        X, W = args[0], args[1]
        bias = args[2] if len(args) > 2 else None
        strides = _attr_ints(attrs, "strides", (1, 1))
        dilations = _attr_ints(attrs, "dilations", (1, 1))
        group = int(attrs.get("group", 1))
        pads = conv_pads(node)
        if xp is np:
            return _np_conv(np.asarray(X, np.float32),
                            np.asarray(W, np.float32), bias, strides,
                            pads, dilations, group)
        from jax import lax

        y = lax.conv_general_dilated(
            X, W, window_strides=strides, padding=list(pads),
            rhs_dilation=dilations, feature_group_count=group,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if bias is not None:
            y = y + xp.reshape(bias, (1, -1, 1, 1))
        return y
    if op in ("MaxPool", "AveragePool"):
        X = args[0]
        kernel, strides = _pool_geometry(node)
        if xp is np:
            fn = np.max if op == "MaxPool" else np.mean
            return _np_pool(np.asarray(X, np.float32), kernel, strides, fn)
        from jax import lax

        dims = (1, 1) + kernel
        strd = (1, 1) + strides
        if op == "MaxPool":
            return lax.reduce_window(X, -xp.inf, lax.max, dims, strd,
                                     "VALID")
        total = lax.reduce_window(X, 0.0, lax.add, dims, strd, "VALID")
        return total / float(kernel[0] * kernel[1])
    if op == "BatchNormalization":
        X, scale, bias, mean, var = args[:5]
        eps = float(attrs.get("epsilon", 1e-5))
        shape = (1, -1) + (1,) * (X.ndim - 2)
        scale, bias, mean, var = (xp.reshape(xp.asarray(a), shape)
                                  for a in (scale, bias, mean, var))
        # inference-mode BN is the folded per-channel affine transform
        return (X - mean) * (scale / xp.sqrt(var + eps)) + bias
    raise UnsupportedOpError([op], sites=[node_site(node)])
    # unreachable after _check_ops


def data_shape(arr) -> tuple:
    return tuple(int(d) for d in arr.shape)


def _run_graph(xp, spec: GraphSpec, X):
    values = {name: xp.asarray(arr)
              for name, arr in spec.initializers.items()}
    values[spec.input_name] = X
    for node in spec.nodes:
        out = _eval_node(xp, node, values)
        for name in node.outputs:
            values[name] = out
    return values[spec.output_name]


def run_graph_reference(spec: GraphSpec, X: np.ndarray) -> np.ndarray:
    """Numpy reference evaluation of the graph — the parity-test oracle
    (and the linear-extraction probe's engine)."""

    return np.asarray(_run_graph(np, spec, np.asarray(X, np.float32)),
                      dtype=np.float32)


def _try_linear(spec: GraphSpec):
    """Lower an affine(+head) graph to ``LinearPredictor`` — or ``None``.

    The affine part is recovered EXACTLY by probing with the identity
    basis: for row-wise affine ``f``, ``b = f(0)`` and ``W = f(I) - b``
    (float32 arithmetic on the same values the graph itself would
    compute, so the lowered model is bit-faithful for Gemm/MatMul/Add
    chains)."""

    ops = [n.op for n in spec.nodes]
    head = None
    if ops and ops[-1] in _LINEAR_HEADS:
        head = _LINEAR_HEADS[ops[-1]]
        body = spec.nodes[:-1]
    else:
        body = spec.nodes
    if not body or not all(n.op in _AFFINE_OPS for n in body):
        return None
    pre = GraphSpec(list(body), spec.initializers, spec.input_name,
                    body[-1].outputs[0], spec.input_dim)
    D = spec.input_dim
    try:
        b = run_graph_reference(pre, np.zeros((1, D), np.float32))
        WI = run_graph_reference(pre, np.eye(D, dtype=np.float32))
    except Exception:
        return None  # shape-incompatible probe: not a row-wise affine map
    if b.ndim != 2 or b.shape[0] != 1 or WI.shape != (D, b.shape[1]):
        return None
    from distributedkernelshap_tpu.models.predictors import LinearPredictor

    W = WI - b  # (D, K)
    # faithfulness probe: a Gemm with transA (or any other batch-coupling
    # oddity) is NOT row-wise affine even though its ops are in the affine
    # set — verify the extraction reproduces the graph before trusting it
    rng = np.random.default_rng(0)
    probe = rng.normal(size=(5, D)).astype(np.float32)
    try:
        want = run_graph_reference(pre, probe)
    except Exception:
        return None
    if want.shape != (5, W.shape[1]) \
            or not np.allclose(probe @ W + b[0], want, atol=1e-4):
        return None
    activation = head or "identity"
    if activation == "sigmoid" and W.shape[1] == 1:
        # binary logistic regression: a single sigmoid logit IS
        # softmax([0, z]) — lift to the two-column softmax form the
        # sklearn predict_proba lift uses, so downstream consumers see
        # [P(0), P(1)] and the linear fast path gets a 2-class head
        W2 = np.concatenate([np.zeros_like(W), W], axis=1)
        b2 = np.concatenate([np.zeros_like(b[0]), b[0]])
        return LinearPredictor(W2, b2, activation="softmax")
    return LinearPredictor(W, b[0], activation=activation,
                           vector_out=W.shape[1] > 1)


class ONNXPredictor(_BasePredictor):
    """Generic lifted ONNX graph: a jittable ``(n, D) -> (n, K)``
    callable over the graph's initializers (kept on-device as jnp
    constants).  Built only for graphs the linear lowering declines —
    MLPs, CNNs and friends.  A real :class:`BasePredictor` (not just
    duck-typed), so ``as_predictor`` passes it through intact and the
    engine sees :meth:`graph_spec` — the hook the DeepSHAP attribution
    path (``attribution/deepshap.py``) classifies on; graphs it cannot
    rule-cover ride the sampled masked-EY path as before."""

    vector_out = True
    supports_masked_ey = False

    def __init__(self, spec: GraphSpec):
        import jax.numpy as jnp

        self.spec = spec
        self._jnp = jnp
        # float weights live on device; integer initializers (Reshape
        # shape vectors) stay host-side numpy — shapes are static under
        # jit, so they must remain concrete, never traced
        self._consts = {
            name: (jnp.asarray(arr, jnp.float32)
                   if np.asarray(arr).dtype.kind == "f"
                   else np.asarray(arr))
            for name, arr in spec.initializers.items()}
        probe = run_graph_reference(spec,
                                    np.zeros((2, spec.input_dim), np.float32))
        self.n_outputs = int(probe.shape[1]) if probe.ndim > 1 else 1
        self.vector_out = probe.ndim > 1

    def __call__(self, X):
        values = dict(self._consts)
        values[self.spec.input_name] = X
        for node in self.spec.nodes:
            out = _eval_node(self._jnp, node, values)
            for name in node.outputs:
                values[name] = out
        out = values[self.spec.output_name]
        return out[:, None] if out.ndim == 1 else out

    def host_fn(self, X: np.ndarray) -> np.ndarray:
        out = run_graph_reference(self.spec, X)
        return out[:, None] if out.ndim == 1 else out

    def graph_spec(self) -> GraphSpec:
        """The lifted graph — the structure the DeepSHAP attribution
        engine consumes (``attribution/deepshap.py`` duck-types on this
        method, like ``tt_structure`` for the tensor-network path)."""

        return self.spec

    def fingerprint_bytes(self) -> bytes:
        """Content bytes for the engine's device-cache / share-key
        fingerprints: two lifted graphs with equal topology and equal
        initializer bytes ARE the same compiled attribution program."""

        parts = [b"onnx-graph",
                 repr([(n.op, n.inputs, n.outputs, sorted(n.attrs.items(),
                                                          key=repr))
                       for n in self.spec.nodes]).encode(),
                 self.spec.input_name.encode(),
                 self.spec.output_name.encode()]
        for name in sorted(self.spec.initializers):
            arr = np.asarray(self.spec.initializers[name])
            parts.append(name.encode())
            parts.append(str(arr.shape).encode())
            parts.append(arr.tobytes())
        return b"".join(parts)


def lift_graph(spec: GraphSpec):
    """Translate a :class:`GraphSpec` into a predictor: a native
    ``LinearPredictor`` when the graph is affine(+head) — the linear fast
    path — else a jittable :class:`ONNXPredictor`.  Raises
    :class:`UnsupportedOpError` listing every op outside the subset."""

    _check_ops(spec)
    linear = _try_linear(spec)
    if linear is not None:
        logger.info("ONNX graph lowered to a native LinearPredictor "
                    "(D=%d, K=%d, %s) — linear fast path", spec.input_dim,
                    linear.n_outputs, linear.activation)
        return linear
    pred = ONNXPredictor(spec)
    logger.info("ONNX graph lifted to a jittable predictor "
                "(%d nodes, D=%d, K=%d)", len(spec.nodes), spec.input_dim,
                pred.n_outputs)
    return pred


# --------------------------------------------------------------------- #
# ONNX ModelProto -> GraphSpec (the optional-import half)
# --------------------------------------------------------------------- #


def _require_onnx():
    try:
        import onnx  # noqa: F401

        return onnx
    except ImportError as e:
        raise ImportError(
            "ONNX ingest needs the optional 'onnx' package "
            "(requirements_advanced.txt); the rest of the registry works "
            "without it") from e


def graph_spec_from_onnx(model) -> GraphSpec:
    """Decode an ONNX ``ModelProto`` into a :class:`GraphSpec`."""

    onnx = _require_onnx()
    from onnx import numpy_helper

    graph = model.graph
    initializers = {init.name: np.asarray(numpy_helper.to_array(init))
                    for init in graph.initializer}
    dynamic_inputs = [i for i in graph.input
                      if i.name not in initializers]
    if len(dynamic_inputs) != 1:
        raise ValueError(
            f"expected exactly one dynamic graph input, got "
            f"{[i.name for i in dynamic_inputs]}")
    if len(graph.output) != 1:
        raise ValueError(
            f"expected exactly one graph output, got "
            f"{[o.name for o in graph.output]}")
    inp = dynamic_inputs[0]
    dims = inp.type.tensor_type.shape.dim
    if len(dims) != 2 or not dims[1].dim_value:
        raise ValueError(
            "expected a (batch, features) input with a static feature "
            "dim; got "
            + str([d.dim_value or d.dim_param for d in dims]))
    nodes = []
    for node in graph.node:
        attrs = {a.name: onnx.helper.get_attribute_value(a)
                 for a in node.attribute}
        nodes.append(NodeSpec(node.op_type, tuple(node.input),
                              tuple(node.output), attrs, node.name))
    return GraphSpec(nodes, initializers, inp.name, graph.output[0].name,
                     int(dims[1].dim_value))


def lift_onnx(source):
    """Lift an ONNX model — a ``ModelProto``, serialized ``bytes``, or a
    file path — into a predictor (see :func:`lift_graph`)."""

    onnx = _require_onnx()
    if isinstance(source, (bytes, bytearray)):
        model = onnx.load_model_from_string(bytes(source))
    elif isinstance(source, str):
        model = onnx.load(source)
    else:
        model = source
    return lift_graph(graph_spec_from_onnx(model))
