"""Multi-tenant model registry: many models, one fleet, hot-swappable.

Every server in the tree used to bind exactly ONE predictor at build time
(``serve_explainer``), so a real multi-user service needed a fleet per
model.  The registry turns the single-model server into a gateway
(ROADMAP item 4, grounded in ONNXExplainer's format-generic framework):

* **Ingest & classify** — :meth:`ModelRegistry.register` accepts any
  fitted serving model (built from the existing lifts — sklearn / xgb /
  lgbm / torch / TT / linear — or the new ONNX ingester,
  ``registry/onnx_lift.py``) and classifies it onto its engine path with
  the ONE shared :func:`~distributedkernelshap_tpu.registry.classify.
  classify_path`.
* **Per-model namespaces** — each ``(model_id, version)`` gets a content
  fingerprint (``model_id@vN:<digest>``) pinned onto the serving model,
  which drives the result-cache key (explicit ``fingerprint`` wins in
  ``scheduling/result_cache.model_fingerprint``), and a compile-cache
  signature prefix (``model=<label>`` via ``runtime/compile_cache.
  shape_signature``) for its warmup-ladder rungs.  Plan-constant /
  exact-path device caches key on the engine objects themselves, which
  are per-version here — no cross-tenant aliasing by construction.
* **Per-tenant quotas** — a :class:`TenantQuota` (token bucket + in-flight
  bound, keyed by model_id) on TOP of the server's per-client buckets: a
  flooding tenant sheds with 429 ``tenant_*`` reasons while other
  tenants' admission is untouched.
* **Hot-swap** — registering version N+1 of an id warms it through the
  attached server's compile ladder, atomically flips the active version,
  and drains version N: in-flight requests pinned the version that
  admitted them, so they finish on it — zero lost or changed answers —
  and the drained version is then retired and its device caches dropped.

The registry is serving-agnostic (no server import at module scope); the
server attaches itself via :meth:`attach_server` and reads per-request
state through :meth:`resolve` / :class:`RegisteredModel`.
"""

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.registry.classify import classify_path
from distributedkernelshap_tpu.scheduling.admission import TokenBucket
from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.scheduling.result_cache import (
    model_fingerprint,
)

logger = logging.getLogger(__name__)


class TenantQuota:
    """Per-tenant admission bounds: a request-rate token bucket and/or an
    in-flight bound (queued + executing requests for the tenant — the
    registry's queue bound).  Either knob may be ``None`` (off)."""

    def __init__(self, rate_per_s: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_inflight: Optional[int] = None):
        self.rate_per_s = rate_per_s
        self.burst = burst if burst is not None else (
            rate_per_s if rate_per_s else None)
        self.max_inflight = max_inflight
        self._bucket = (TokenBucket(rate_per_s, self.burst)
                        if rate_per_s else None)

    def clone(self) -> "TenantQuota":
        """A fresh quota with the same parameters but its OWN token
        bucket — the registry clones ``default_quota`` per tenant, or a
        shared default bucket would let one tenant drain every other
        tenant's allowance (exactly the interference quotas exist to
        prevent)."""

        return TenantQuota(rate_per_s=self.rate_per_s, burst=self.burst,
                           max_inflight=self.max_inflight)

    def admit(self, inflight: int) -> Tuple[bool, str, float]:
        """``(admitted, reason, retry_after_s)`` for one request of a
        tenant currently holding ``inflight`` requests."""

        if self.max_inflight is not None and inflight >= self.max_inflight:
            return False, "tenant_queue_full", 1.0
        if self._bucket is not None:
            ok, retry = self._bucket.try_acquire(1.0)
            if not ok:
                return False, "tenant_rate_limited", max(0.05, retry)
        return True, "", 0.0

    def describe(self) -> Dict:
        return {"rate_per_s": self.rate_per_s, "burst": self.burst,
                "max_inflight": self.max_inflight}


class RegisteredModel:
    """One ``(model_id, version)``: the fitted serving model plus its
    namespace facts (fingerprint, engine path) and lifecycle state.

    Requests PIN the RegisteredModel that admitted them (the server
    stores it on the pending request), so a hot-swap never changes an
    in-flight answer: dispatch, cache keying and metrics all read the
    pinned version, and :meth:`drain` waits for the pin count to reach
    zero before the old version is retired."""

    def __init__(self, model_id: str, version: int, model,
                 fingerprint: str, path: str, path_reason: str,
                 quota: Optional[TenantQuota] = None):
        self.model_id = model_id
        self.version = int(version)
        self.model = model
        self.fingerprint = fingerprint
        self.path = path
        self.path_reason = path_reason
        self.quota = quota
        self.state = "active"
        # cross-tenant shared-program identity (ops/explain.
        # shared_program_key): tenants with EQUAL keys dispatch the
        # identical compiled program over identical device constants, so
        # the server may coalesce their rows into one padded device call
        # bit-identically.  None = never share.
        self.share_key: Optional[str] = None
        # set once a server ladder has compiled this version's programs
        # (register-time warm or the start-time ladder) — the start-time
        # ladder skips already-warm models instead of re-running them
        self.warmed = False
        self.created_at = time.time()
        self._cond = lockwitness.make_condition("registry.model")
        self._inflight = 0
        # per-tenant accounting, rendered via the server's registry
        # callbacks (dks_registry_requests_total etc.)
        self.requests = 0
        self.errors = 0
        self.seconds = 0.0

    @property
    def label(self) -> str:
        return f"{self.model_id}@v{self.version}"

    # -- in-flight pinning -------------------------------------------- #

    def acquire(self) -> None:
        with self._cond:
            self._inflight += 1

    def release(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            if self._inflight == 0:
                self._cond.notify_all()

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until every request pinned to this version has answered.
        Returns whether the drain completed inside ``timeout_s``."""

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.5))
        return True

    def record_answer(self, elapsed_s: float, error: bool) -> None:
        with self._cond:
            self.requests += 1
            self.seconds += float(elapsed_s)
            if error:
                self.errors += 1

    def describe(self) -> Dict:
        with self._cond:
            return {
                "model_id": self.model_id, "version": self.version,
                "state": self.state, "path": self.path,
                "path_reason": self.path_reason,
                "fingerprint": self.fingerprint,
                "inflight": self._inflight, "requests": self.requests,
                "errors": self.errors,
                "quota": self.quota.describe() if self.quota else None,
                # truncated like the fingerprint: enough for an operator
                # to SEE which tenants coalesce, not a secret
                "share_key": (self.share_key[:16]
                              if self.share_key else None),
            }


class ModelRegistry:
    """Thread-safe registry of served models, keyed by ``model_id`` with
    monotonically increasing versions (see module docstring).

    Parameters
    ----------
    default_model_id
        The id served when a request names no model.  ``None`` (default)
        resolves to the FIRST registered id.
    default_quota
        :class:`TenantQuota` applied to models registered without their
        own (``None`` = unlimited — the single-tenant behaviour).
    drain_timeout_s
        How long a hot-swap waits for the displaced version's in-flight
        requests before giving up on retiring it (the requests still
        answer; only the retire bookkeeping is abandoned, loudly).
    """

    def __init__(self, default_model_id: Optional[str] = None,
                 default_quota: Optional[TenantQuota] = None,
                 drain_timeout_s: float = 30.0):
        self._lock = lockwitness.make_lock("registry.models")
        # registrations serialise END TO END (version allocation, warm,
        # insert, drain): two concurrent register() calls for one id
        # would otherwise allocate the same auto-version during the
        # seconds-long unlocked warm window and silently overwrite each
        # other.  A separate lock from _lock so draining requests (which
        # resolve/release under _lock) never deadlock a registration.
        self._register_lock = lockwitness.make_lock("registry.register")
        #: {model_id: {"active": RegisteredModel, "versions": {v: rm}}}
        self._models: Dict[str, Dict] = {}
        self._order: List[str] = []
        self.default_model_id = default_model_id
        self.default_quota = default_quota
        self.drain_timeout_s = float(drain_timeout_s)
        self._server = None
        self._flight = flightrec()
        # shed / swap accounting for the dks_registry_* callbacks
        self._sheds: Dict[Tuple[str, str], float] = {}
        self._swaps: Dict[str, float] = {}
        # ACTIVE versions per shared-program key: the server coalesces a
        # tenant onto a share group only when it actually has peers
        # (share_peers > 1) — a lone eligible tenant keeps its per-model
        # group identity, so its quota keeps capping its per-cycle take
        self._share_counts: Dict[str, int] = {}

    # -- serving attachment ------------------------------------------- #

    def attach_server(self, server) -> None:
        """Called by the server that routes through this registry; used
        to warm newly registered versions through ITS compile ladder."""

        self._server = server

    # -- ingest -------------------------------------------------------- #

    def register(self, model_id: str, model, version: Optional[int] = None,
                 quota: Optional[TenantQuota] = None,
                 warm: Optional[bool] = None) -> RegisteredModel:
        """Register (or hot-swap) one model.

        ``model`` is a fitted serving model (``KernelShapModel``-like).
        ``version`` defaults to ``previous + 1`` (1 for a new id).
        ``warm`` defaults to warming whenever a server is attached; the
        warm runs BEFORE the version flips, so the first routed request
        lands on compiled executables.  Returns the
        :class:`RegisteredModel`.
        """

        if not model_id or "," in model_id or "=" in model_id:
            # the label feeds compile signatures (model=<id>,rows=...)
            # and metric label values; keep it delimiter-free
            raise ValueError(
                f"model_id must be a non-empty string without ','/'=' "
                f"(got {model_id!r})")
        if not hasattr(model, "explain_batch"):
            raise ValueError(
                "register() needs a fitted serving model exposing "
                "explain_batch (KernelShapModel / BatchKernelShapModel)")
        with self._register_lock:
            return self._register_locked(model_id, model, version, quota,
                                         warm)

    def _register_locked(self, model_id, model, version, quota, warm
                         ) -> RegisteredModel:
        with self._lock:
            entry = self._models.get(model_id)
            prev = entry["active"] if entry else None
            if version is None:
                version = (max(entry["versions"]) + 1) if entry else 1
            elif entry and version in entry["versions"]:
                raise ValueError(
                    f"{model_id} version {version} already registered")
        path, reason = self._deployment_path(model)
        content = model_fingerprint(model, count_weak=False)
        if quota is None and prev is not None:
            # a hot swap is a model update, not a policy change: the
            # tenant KEEPS its quota (same object — bucket state carries
            # across the flip) unless the caller explicitly passes one
            quota = prev.quota
        elif quota is None and self.default_quota is not None:
            quota = self.default_quota.clone()  # per-tenant bucket
        rm = RegisteredModel(
            model_id, version, model,
            fingerprint=f"{model_id}@v{version}:{content[:24]}",
            path=path, path_reason=reason, quota=quota)
        try:
            # shared-program eligibility probe (never fails an ingest):
            # content-identical tenants land on EQUAL keys and may share
            # padded device calls (cross-tenant continuous batching)
            from distributedkernelshap_tpu.ops.explain import (
                shared_program_key,
            )

            rm.share_key = shared_program_key(model)
        except Exception:
            logger.debug("shared-program probe failed for %s", rm.label,
                         exc_info=True)
        # the pinned attribute is what scheduling/result_cache's
        # model_fingerprint returns, so every cache key is scoped to this
        # (model_id, version, content) — and survives a restart
        model.fingerprint = rm.fingerprint
        # relabel the engine's ledger-tracked device caches to this
        # tenant/version so dks_device_bytes attributes engine consts to
        # the model that owns them (best-effort: stub models have no
        # engine, a pre-ledger engine no rebind)
        try:
            engine = getattr(getattr(model, "explainer", model),
                             "_explainer", None)
            for cache_attr in ("_dev_cache", "_plan_consts_cache"):
                cache = getattr(engine, cache_attr, None)
                rebind = getattr(cache, "rebind", None)
                if rebind is not None:
                    rebind(model=model_id, version=version, path=path)
        except Exception:
            logger.debug("ledger rebind failed for %s", rm.label,
                         exc_info=True)
        # warm BEFORE the flip: the new version compiles its ladder while
        # the old one keeps serving, so the swap is hitless
        server = self._server
        if warm is None:
            warm = server is not None
        if warm and server is not None:
            try:
                server._warm_model(rm)
            except Exception:
                logger.exception("warmup of %s failed; serving it cold",
                                 rm.label)
        # canary drift sentinel (observability/quality.py): replay the
        # tenant's golden canary set against the INCOMING version before
        # traffic moves, then re-capture the baseline from the version
        # about to serve — the model_swap event below carries the
        # quantified verdict.  Best-effort like warmup: a sentinel
        # failure must never block a registration.
        drift = None
        quality = getattr(server, "_quality", None) \
            if server is not None else None
        if quality is not None:
            try:
                drift = quality.swap_check(model_id, rm.model,
                                           fingerprint=rm.fingerprint)
            except Exception:
                logger.exception("canary swap check for %s failed", rm.label)
        with self._lock:
            entry = self._models.setdefault(
                model_id, {"active": None, "versions": {}})
            entry["versions"][version] = rm
            entry["active"] = rm
            if model_id not in self._order:
                self._order.append(model_id)
            self._swaps[model_id] = self._swaps.get(model_id, 0.0) + 1.0
            # share-peer accounting tracks ACTIVE versions only: the
            # displaced version leaves its share group at the flip (its
            # still-pinned requests dispatch under their per-model key)
            if prev is not None and prev.share_key:
                n = self._share_counts.get(prev.share_key, 0) - 1
                if n > 0:
                    self._share_counts[prev.share_key] = n
                else:
                    self._share_counts.pop(prev.share_key, None)
            if rm.share_key:
                self._share_counts[rm.share_key] = \
                    self._share_counts.get(rm.share_key, 0) + 1
        self._flight.record("model_swap", model=model_id,
                            from_version=(prev.version if prev else None),
                            to_version=version, path=rm.path,
                            fingerprint=rm.fingerprint,
                            canary_drift=(drift or {}).get("drift"),
                            canary_verdict=(drift or {}).get("verdict"),
                            canary_rows=(drift or {}).get("rows"))
        logger.info("registered %s (path=%s: %s)%s", rm.label, rm.path,
                    rm.path_reason,
                    f"; draining v{prev.version}" if prev else "")
        if prev is not None:
            prev.state = "draining"
            if prev.drain(self.drain_timeout_s):
                prev.state = "retired"
                reset = getattr(prev.model, "reset", None)
                if reset is not None:
                    try:
                        reset()  # free the retired version's device caches
                    except Exception:
                        logger.exception("reset of drained %s failed",
                                         prev.label)
                # release the engine itself: the RegisteredModel stays
                # (scalar tallies feed the per-id metric sums and the
                # duplicate-version check) but a nightly-swapping tenant
                # must not accumulate one full model per swap
                prev.model = None
                # stale label retirement: the retired version's
                # version-labeled series (device-seconds) stop rendering
                # — a nightly-swapping tenant must not grow the metric
                # registry by one version's label set per swap
                self._retire_tenant_labels(model_id, version=prev.version)
            else:
                logger.warning(
                    "drain of %s did not complete within %.0fs (%d "
                    "requests still pinned); they will still answer on "
                    "their admitted version", prev.label,
                    self.drain_timeout_s, prev.inflight)
        self._notify_server_roster_changed()
        return rm

    def unregister(self, model_id: str,
                   drain_timeout_s: Optional[float] = None) -> bool:
        """Remove one tenant for good: the id leaves the roster FIRST
        (no new resolves — routed requests 404 with the remaining
        roster), the active version drains (in-flight pinned requests
        still answer), its device caches drop, and every metric series
        labeled with the tenant retires (``MetricsRegistry.
        retire_labels`` via the cost meter) so deleted tenants stop
        accumulating label space forever.  Returns whether the drain
        completed inside the timeout (the removal happens either way;
        an incomplete drain's stragglers still answer on their pinned
        version)."""

        with self._register_lock:
            with self._lock:
                entry = self._models.pop(model_id, None)
                if entry is None:
                    raise KeyError(f"unknown model id {model_id!r}")
                self._order.remove(model_id)
                if self.default_model_id == model_id:
                    self.default_model_id = None
                active = entry["active"]
                if active is not None and active.share_key:
                    n = self._share_counts.get(active.share_key, 0) - 1
                    if n > 0:
                        self._share_counts[active.share_key] = n
                    else:
                        self._share_counts.pop(active.share_key, None)
                self._sheds = {k: v for k, v in self._sheds.items()
                               if k[0] != model_id}
                self._swaps.pop(model_id, None)
            drained = True
            if drain_timeout_s is None:
                drain_timeout_s = self.drain_timeout_s
            for rm in entry["versions"].values():
                if rm.model is None:
                    continue
                rm.state = "draining"
                if rm.drain(drain_timeout_s):
                    rm.state = "retired"
                    reset = getattr(rm.model, "reset", None)
                    if reset is not None:
                        try:
                            reset()
                        except Exception:
                            logger.exception("reset of removed %s failed",
                                             rm.label)
                    rm.model = None
                else:
                    drained = False
                    logger.warning(
                        "unregister(%s): drain of %s incomplete (%d "
                        "requests still pinned); they answer on their "
                        "pinned version", model_id, rm.label, rm.inflight)
            self._retire_tenant_labels(model_id)
            self._flight.record("model_removed", model=model_id,
                                drained=drained)
            logger.info("unregistered %s (drained=%s)", model_id, drained)
        self._notify_server_roster_changed()
        return drained

    def _retire_tenant_labels(self, model_id: str,
                              version: Optional[int] = None) -> None:
        """Drop a removed tenant's (or a retired version's) stale metric
        label values on the attached server's registry — best-effort
        cleanup; a failure is logged, never raised into the swap/remove
        path."""

        server = self._server
        if server is None:
            return
        try:
            meter = getattr(server, "_costmeter", None)
            if meter is not None:
                meter.retire_tenant(model_id, version=version)
            # drop the tenant's (or the retired version's) device-memory
            # ledger accounts too, so dks_device_bytes{model=...} stops
            # rendering alongside the cost series
            from distributedkernelshap_tpu.observability.memledger import (
                memledger,
            )

            memledger().retire(model_id, version=version)
            if version is None:
                server.metrics.retire_labels("dks_serve_padded_rows_total",
                                             {"model": model_id})
                # quality plane: drop the tenant's canary baseline,
                # shadow-error series and dks_quality_* label values
                quality = getattr(server, "_quality", None)
                if quality is not None:
                    quality.retire_tenant(model_id, registry=server.metrics)
        except Exception:
            logger.exception("label retirement for %s failed", model_id)

    def _notify_server_roster_changed(self) -> None:
        """Refresh the attached server's templated per-tenant SLOs after
        a registration or removal (no-op without a server, or when the
        operator pinned an explicit SLO set)."""

        server = self._server
        if server is None:
            return
        refresh = getattr(server, "_refresh_tenant_slos", None)
        if refresh is not None:
            refresh()

    @staticmethod
    def _deployment_path(model) -> Tuple[str, str]:
        """``(path, reason)`` for what this deployment actually SERVES.

        ``classify_path`` states what the predictor structurally admits;
        the serving wrapper's resolved ``explain_path`` states what the
        deployment runs after pinned ``explain_kwargs`` and the
        exact-auto opt-out — /statusz and ``dks_registry_models`` must
        report the latter, or an operator debugging estimator variance
        would be told a sampled tenant is on an exact path."""

        decision = classify_path(model)
        served = getattr(model, "explain_path", None)
        if served == "exact":
            return "exact_tree", decision.reason
        if served == "exact_tn":
            return "exact_tn", decision.reason
        if served == "deepshap":
            return "deepshap", decision.reason
        if decision.path in ("exact_tree", "exact_tn", "deepshap") \
                and served == "sampled":
            return "sampled", (f"{decision.path} structurally available "
                               f"but deployment serves sampled "
                               f"({getattr(model, 'explain_path_reason', 'pinned')})")
        return decision.path, decision.reason

    # -- request-path reads -------------------------------------------- #

    def resolve(self, model_id: Optional[str] = None, pin: bool = False
                ) -> Optional[RegisteredModel]:
        """The active version for ``model_id`` (default: the registry's
        default id), or ``None`` when unknown / nothing registered.

        ``pin=True`` (the serving handler) acquires the in-flight pin
        ATOMICALLY with the lookup: a hot-swap's drain can then never
        observe zero pins between a request resolving a version and
        pinning it — i.e. the admitted version cannot be retired (and
        its model released) under an already-routed request.  The caller
        owns the matching ``release()``."""

        with self._lock:
            if model_id is None:
                model_id = self.default_model_id or (
                    self._order[0] if self._order else None)
            entry = self._models.get(model_id) if model_id else None
            rm = entry["active"] if entry else None
            if rm is not None and pin:
                rm.acquire()
            return rm

    def admit(self, rm: RegisteredModel,
              exclude_self: bool = False) -> Tuple[bool, str, float]:
        """Apply the tenant's quota to one request (``(admitted, reason,
        retry_after_s)``); records the shed for the per-model counter.
        ``exclude_self=True`` when the caller already holds ITS pin on
        ``rm`` (the serving handler pins at resolve time), so the
        in-flight bound judges the OTHER requests."""

        if rm.quota is None:
            return True, "", 0.0
        inflight = rm.inflight - (1 if exclude_self else 0)
        ok, reason, retry = rm.quota.admit(max(0, inflight))
        if not ok:
            with self._lock:
                key = (rm.model_id, reason)
                self._sheds[key] = self._sheds.get(key, 0.0) + 1.0
        return ok, reason, retry

    def share_peers(self, share_key: Optional[str]) -> int:
        """ACTIVE versions currently carrying ``share_key`` — the server
        coalesces tenants onto one shared-program dispatch group only
        when this exceeds 1 (a lone eligible tenant keeps its per-model
        group, so its quota's per-cycle packing cap still applies)."""

        if not share_key:
            return 0
        with self._lock:
            return self._share_counts.get(share_key, 0)

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def active_models(self) -> List[RegisteredModel]:
        with self._lock:
            return [self._models[mid]["active"] for mid in self._order
                    if self._models[mid]["active"] is not None]

    def reset_all(self) -> None:
        """Drop device-resident state of every active model (the serving
        watchdog's wedge recovery, fleet-wide)."""

        for rm in self.active_models():
            reset = getattr(rm.model, "reset", None)
            if reset is not None:
                try:
                    reset()
                except Exception:
                    logger.exception("reset of %s failed", rm.label)

    # -- observability ------------------------------------------------- #

    def _all_versions(self) -> Dict[str, List[RegisteredModel]]:
        with self._lock:
            return {mid: list(self._models[mid]["versions"].values())
                    for mid in self._order}

    def metric_models(self) -> Dict[tuple, float]:
        return {(rm.model_id, str(rm.version), rm.path): 1.0
                for rm in self.active_models()}

    def metric_requests(self) -> Dict[tuple, float]:
        # summed across ALL versions of an id: a counter backed by only
        # the active version would DROP at every hot swap (a Prometheus
        # counter reset) and lose the retired versions' tallies
        return {(mid,): float(sum(rm.requests for rm in versions))
                for mid, versions in self._all_versions().items()}

    def metric_seconds(self) -> Dict[tuple, float]:
        return {(mid,): sum(rm.seconds for rm in versions)
                for mid, versions in self._all_versions().items()}

    def metric_inflight(self) -> Dict[tuple, float]:
        # draining versions still hold pins; the gauge must count them
        return {(mid,): float(sum(rm.inflight for rm in versions))
                for mid, versions in self._all_versions().items()}

    def metric_sheds(self) -> Dict[tuple, float]:
        with self._lock:
            return {k: v for k, v in self._sheds.items()}

    def metric_swaps(self) -> Dict[tuple, float]:
        with self._lock:
            return {(mid,): n for mid, n in self._swaps.items()}

    def statusz_panel(self) -> Dict:
        """The ``/statusz`` registry block: every id's active version
        with path/fingerprint/in-flight, plus non-retired older versions
        still draining."""

        panel = {"default_model_id": self.default_model_id
                 or (self._order[0] if self._order else None),
                 "models": []}
        with self._lock:
            entries = [(mid, dict(self._models[mid]["versions"]),
                        self._models[mid]["active"])
                       for mid in self._order]
        for mid, versions, active in entries:
            doc = active.describe() if active else {"model_id": mid}
            doc["versions"] = sorted(versions)
            doc["draining"] = [rm.version for rm in versions.values()
                               if rm.state == "draining"]
            panel["models"].append(doc)
        return panel
