"""The ONE engine-path classifier for registered models.

Before the registry, the decision "which evaluation path should serve this
predictor" lived inline in ``serving/wrappers.KernelShapModel.
_resolve_explain_path`` (PR 7 added the exact-TreeSHAP arm, PR 9 the exact
tensor-network arm) and nothing named the linear fast path at all — it was
an emergent property of the engine's ``linear_decomposition`` branch.  The
multi-tenant gateway needs the decision as a first-class, reusable fact:
ingest classifies every registered ``(model_id, version)`` once, the
serving wrappers keep auto-selecting from the same logic, and ``/statusz``
/ ``dks_registry_models`` render the result per tenant.

Paths (:data:`ENGINE_PATHS`):

* ``linear`` — the predictor exposes a ``(W, b, activation)``
  decomposition, so the engine collapses the KernelSHAP synthetic tensor
  into three einsums and small batches ride the plan-constant device
  cache (the MXU fast path; estimator still sampled, but the plan is
  closed-form cheap).
* ``exact_tree`` — lifted tree ensemble with raw-margin outputs at
  identity link: closed-form interventional TreeSHAP, no sampling.
* ``exact_tn`` — tensor-train-structured predictor passing every
  readiness gate (``ops/tensor_shap.tn_exact_ready``): exact Shapley by
  DP contraction.
* ``deepshap`` — predictor carrying a lifted neural graph whose every
  node has an attribution rule (``attribution/deepshap.py``): DeepSHAP
  multiplier backprop, sampling-free — exact Shapley for
  coalition-stable piecewise-linear nets, exact-completeness DeepLIFT
  attribution otherwise.
* ``sampled`` — the generic masked-EY KernelSHAP estimator (everything
  else, including TT predictors and neural graphs that fail a readiness
  gate — the reason is carried so callers can count it).
"""

from typing import NamedTuple, Optional

ENGINE_PATHS = ("linear", "exact_tree", "exact_tn", "deepshap", "sampled")


class PathDecision(NamedTuple):
    """``path`` is one of :data:`ENGINE_PATHS`; ``reason`` is a short
    human phrase for /statusz and logs; ``tn_fallback`` carries the
    ``tn_exact_ready`` reason when a TT-structured predictor stays
    sampled, ``deepshap_fallback`` the ``deepshap_ready`` reason when a
    graph-bearing predictor does (callers decide whether to count them —
    the serving wrapper does, a pure classification probe does not)."""

    path: str
    reason: str
    tn_fallback: Optional[str] = None
    deepshap_fallback: Optional[str] = None


def serving_engine(model):
    """The fitted engine behind a serving model / explainer / engine
    (``DistributedExplainer`` wraps the real engine one level down), or
    ``None`` when ``model`` exposes none — one extraction for the
    wrappers, the registry and the classifier."""

    explainer = getattr(model, "explainer", model)
    engine = getattr(explainer, "_explainer", explainer)
    if engine is not None and not hasattr(engine, "predictor"):
        engine = getattr(engine, "engine", None)
    return engine if hasattr(engine, "predictor") else None


def share_eligible(model):
    """The fitted engine behind ``model`` IF the deployment qualifies for
    cross-tenant shared-program dispatch, else ``None``.

    Sharing coalesces several tenants' request rows into ONE padded
    device call, so eligibility is exactly the bit-identity gate
    (docs/MULTITENANCY.md): the serving wrapper must declare per-row
    reduction scope (``per_row_reduction`` — every request's phi depends
    only on its own rows plus X-independent constants, true of all four
    engine paths but NOT of arbitrary stub models), and the pinned
    explain options must be limited to ``nsamples`` — ``interactions``
    and active ``l1_reg`` ride sync fallbacks with request-coupled
    control flow.  The engine itself carries the compatibility facts
    (content fingerprint, plan seed, config) that
    :func:`~distributedkernelshap_tpu.ops.explain.shared_program_key`
    digests into the share key two tenants must MATCH on."""

    if not getattr(model, "per_row_reduction", False):
        return None
    kwargs = getattr(model, "explain_kwargs", None)
    if kwargs is None:
        return None
    if any(v for k, v in kwargs.items() if k != "nsamples"):
        return None
    return serving_engine(model)


def classify_path(model, link: Optional[str] = None, G=None,
                  target_chunk_elems: Optional[int] = None) -> PathDecision:
    """Classify ``model`` onto its engine path.

    ``model`` may be a fitted serving model (``KernelShapModel``-like), a
    fitted explainer/engine, or a bare predictor — for a bare predictor,
    ``link``/``G`` default to ``"identity"``/``None`` (no grouping), the
    registry's ingest-time view.  Never raises: a probe failure
    classifies as ``sampled`` with the failure named in ``reason``.
    """

    try:
        return _classify(model, link, G, target_chunk_elems)
    except Exception as e:  # classification must never fail an ingest
        return PathDecision("sampled", f"classification probe failed: {e}")


def _classify(model, link, G, target_chunk_elems) -> PathDecision:
    from distributedkernelshap_tpu.ops.tensor_shap import (
        supports_exact_tn,
        tn_exact_ready,
    )
    from distributedkernelshap_tpu.ops.treeshap import supports_exact

    engine = serving_engine(model)
    if engine is not None:
        pred = engine.predictor
        if link is None:
            link = engine.config.link
        if G is None:
            G = engine.G
        if target_chunk_elems is None:
            target_chunk_elems = engine.config.shap.target_chunk_elems
    else:
        pred = model
    if link is None:
        link = "identity"

    if supports_exact(pred):
        if link == "identity":
            return PathDecision(
                "exact_tree",
                f"lifted {type(pred).__name__} with raw-margin outputs")
        return PathDecision(
            "sampled", f"tree ensemble at link={link!r} (exact TreeSHAP "
                       "explains the raw margin only)")
    if supports_exact_tn(pred):
        import numpy as np

        G_eff = G
        if G_eff is None:
            # ingest-time classification of a bare TT predictor: identity
            # grouping, one site per feature — the shape the contraction
            # actually serves
            M = getattr(pred, "n_features", None)
            struct = getattr(pred, "tt_structure", lambda: None)()
            if M is None and struct is not None:
                M = struct["M"]
            G_eff = np.eye(int(M), dtype=np.float32) if M else None
        reason = tn_exact_ready(pred, link, G_eff, target_chunk_elems) \
            if G_eff is not None else "grouping"
        if reason is None:
            return PathDecision(
                "exact_tn",
                f"tensor-train structure (rank "
                f"{pred.tt_structure()['rank']}) at identity link")
        return PathDecision(
            "sampled", f"TT structure present but not exact-ready "
                       f"({reason})", tn_fallback=reason)
    from distributedkernelshap_tpu.attribution.deepshap import (
        graph_spec_of,
        deepshap_ready,
    )

    if graph_spec_of(pred) is not None:
        reason = deepshap_ready(pred, link, G, target_chunk_elems)
        if reason is None:
            spec = pred.graph_spec()
            return PathDecision(
                "deepshap",
                f"lifted neural graph ({len(spec.nodes)} nodes, "
                f"D={spec.input_dim}): DeepSHAP backprop attribution")
        return PathDecision(
            "sampled", f"neural graph present but not DeepSHAP-ready "
                       f"({reason})", deepshap_fallback=reason)
    if getattr(pred, "linear_decomposition", None) is not None:
        W, _, activation = pred.linear_decomposition
        return PathDecision(
            "linear", f"linear decomposition (D={int(W.shape[0])}, "
                      f"K={int(W.shape[1])}, {activation}) — "
                      "plan-constant fast path")
    return PathDecision(
        "sampled", f"generic predictor ({type(pred).__name__}): "
                   "masked-EY sampled estimator")
