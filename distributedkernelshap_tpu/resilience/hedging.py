"""Tail-latency request hedging: policy + per-class quantile tracking.

A single slow replica (background compaction, a first compile, a noisy
neighbour) inflates the fan-in's tail latency far beyond the fleet
median — the classic "tail at scale" problem.  The cure is hedging: when
a request has waited past the class's observed latency quantile, dispatch
a second copy to a different replica and take whichever answers first.

Hedging is safe here because explanations are deterministic and
content-addressed (``scheduling/result_cache.py``): the duplicate
execution produces a bit-identical payload under the same cache key, the
proxy returns exactly one answer per client request, and the loser's
response is discarded — double execution can never double-count or
surface two answers.  The only cost is the duplicated device work, which
the delay bounds to the slowest few percent of requests.

The policy is consulted by
:meth:`~distributedkernelshap_tpu.serving.replicas.FanInProxy.handle_explain`;
this module holds the policy + tracker so they are testable without HTTP.
"""

import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["LatencyQuantiles", "HedgePolicy"]


class LatencyQuantiles:
    """Streaming per-class latency quantiles over a sliding window.

    A bounded deque per class (default 512 samples) — at fan-in request
    rates the window spans recent-enough history, and an exact quantile
    over <= 512 floats is cheaper than maintaining a sketch.  Thread-safe.
    """

    def __init__(self, window: int = 512):
        self.window = int(window)
        self._samples: Dict[str, deque] = {}
        self._lock = threading.Lock()

    def observe(self, klass: str, seconds: float) -> None:
        with self._lock:
            dq = self._samples.get(klass)
            if dq is None:
                dq = self._samples[klass] = deque(maxlen=self.window)
            dq.append(float(seconds))

    def quantile(self, klass: str, q: float) -> Optional[float]:
        """The q-quantile of the class's window, or ``None`` with no
        samples (the policy falls back to its initial delay)."""

        with self._lock:
            dq = self._samples.get(klass)
            if not dq:
                return None
            ordered = sorted(dq)
        # nearest-rank over the (<=window)-sample sort — exact and cheap
        rank = min(len(ordered) - 1,
                   max(0, int(round(q * (len(ordered) - 1)))))
        return ordered[rank]

    def count(self, klass: str) -> int:
        with self._lock:
            dq = self._samples.get(klass)
            return len(dq) if dq else 0


class HedgePolicy:
    """When and whether to hedge.

    ``delay_for`` returns the wait before dispatching the hedge: the
    class's ``quantile`` of observed latency (default p95 — hedge only
    the slowest ~5%), clamped to ``[min_delay_s, max_delay_s]``.  Before
    ``min_samples`` observations exist for the class the tracker is too
    noisy to trust, so ``initial_delay_s`` applies — choose it near the
    expected worst-case healthy latency so cold-start traffic does not
    hedge-storm a fleet that is merely compiling.
    """

    def __init__(self, quantile: float = 0.95,
                 min_delay_s: float = 0.05,
                 max_delay_s: float = 30.0,
                 initial_delay_s: float = 2.0,
                 min_samples: int = 10):
        if not 0.0 < quantile < 1.0:
            raise ValueError("quantile must be in (0, 1)")
        if min_delay_s <= 0 or max_delay_s < min_delay_s:
            raise ValueError("need 0 < min_delay_s <= max_delay_s")
        self.quantile = float(quantile)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.initial_delay_s = float(initial_delay_s)
        self.min_samples = int(min_samples)

    def delay_for(self, tracker: LatencyQuantiles, klass: str) -> float:
        if tracker.count(klass) < self.min_samples:
            delay = self.initial_delay_s
        else:
            delay = tracker.quantile(klass, self.quantile)
            if delay is None:
                # min_samples=0 with an empty window: there is no quantile
                # to trust yet — fall back like the cold-start path instead
                # of crashing the proxy handler on max(float, None)
                delay = self.initial_delay_s
        return min(self.max_delay_s, max(self.min_delay_s, delay))
