"""Replica process supervision: restart-on-crash with crash-loop backoff.

The reference's replicas were Ray Serve actors with ``restartPolicy:
Always`` behind them; a crashed backend respawned and rejoined routing
automatically.  The jax_graft port's ``ReplicaManager`` grew a minimal
restart loop (fixed 1 s backoff, no proxy integration); this module is
its grown-up replacement:

* **crash-loop protection** — a replica that dies immediately after
  every start (poisoned model file, bad device) is restarted with
  exponential backoff + jitter instead of hot-looping spawn/crash cycles
  that burn a CPU and spam logs; an incarnation that stays up
  ``healthy_reset_s`` resets the backoff.
* **membership agreement** — the supervisor marks a dead replica out of
  the fan-in proxy's rotation the moment the process exits, instead of
  letting clients discover the corpse via failed connects; recovery
  stays owned by the proxy's ``/healthz`` prober, so exactly one
  component (the prober) ever declares a replica live, and exactly one
  (the supervisor or a failed connect) declares it dead.
* **elastic membership** — the autoscaler (``serving/autoscaler.py``)
  grows the fleet mid-run (:meth:`ReplicaSupervisor.track` puts a
  freshly spawned worker under supervision) and shrinks it by DRAINING:
  a retired replica (:meth:`ReplicaSupervisor.retire`) exited on
  purpose, so its process exit must NOT trigger a restart — retirement
  is the one exit the crash loop is wrong about.

Used by ``serving/replicas.ReplicaManager``; standalone-usable for any
list of worker ``Popen`` objects plus a spawn function.
"""

import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.flightrec import flightrec

logger = logging.getLogger(__name__)


class RestartPolicy:
    """Exponential backoff with jitter for crash-looping replicas.

    ``delay(n)`` for the n-th CONSECUTIVE crash (n >= 1) is
    ``base_backoff_s * 2**(n-1)`` capped at ``max_backoff_s``, plus
    uniform jitter of ``jitter_frac`` of the delay (jitter decorrelates a
    fleet that all crashed on the same poisoned input, so the restarts
    don't stampede the shared model store / device pool).  Seedable for
    deterministic tests.
    """

    def __init__(self, base_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0,
                 jitter_frac: float = 0.25,
                 healthy_reset_s: float = 60.0,
                 seed: Optional[int] = None):
        if base_backoff_s <= 0 or max_backoff_s < base_backoff_s:
            raise ValueError("need 0 < base_backoff_s <= max_backoff_s")
        if not 0.0 <= jitter_frac <= 1.0:
            raise ValueError("jitter_frac must be in [0, 1]")
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter_frac = float(jitter_frac)
        self.healthy_reset_s = float(healthy_reset_s)
        self._rng = random.Random(seed)

    def delay(self, consecutive_crashes: int) -> float:
        n = max(1, int(consecutive_crashes))
        base = min(self.max_backoff_s,
                   self.base_backoff_s * (2.0 ** (n - 1)))
        return base * (1.0 + self.jitter_frac * self._rng.random())


class ReplicaSupervisor:
    """Monitors worker processes and restarts exited ones.

    Parameters
    ----------
    procs
        The SHARED list of worker ``Popen`` objects — restarts replace
        entries in place, so the owner (``ReplicaManager``) always sees
        the live incarnation.
    spawn
        ``spawn(index) -> Popen`` relaunching one worker.
    proxy
        Optional ``FanInProxy``: on process exit the replica is marked
        out of rotation immediately (``alive = False``); the proxy's own
        prober re-admits it once ``/healthz`` answers.
    policy
        :class:`RestartPolicy`; defaults are production-shaped.
    lock
        Optional externally owned lock serialising respawn against the
        owner's shutdown sweep (``ReplicaManager`` passes its procs
        lock); an internal lock is created otherwise.
    """

    def __init__(self, procs: List, spawn: Callable[[int], object],
                 proxy=None, policy: Optional[RestartPolicy] = None,
                 poll_interval_s: float = 0.5,
                 lock: Optional[threading.Lock] = None):
        self.procs = procs
        self.spawn = spawn
        self.proxy = proxy
        self.policy = policy or RestartPolicy()
        self.poll_interval_s = float(poll_interval_s)
        self.lock = lock or threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-replica crash bookkeeping, guarded by its OWN lock
        # (DKS-C001: track/retire/stats arrive from the autoscaler and
        # statusz threads while _tick mutates).  Deliberately distinct
        # from self.lock — the owner may pass its procs lock there and
        # call is_retired() while holding it (ReplicaManager.
        # spawn_replica does), so reusing it here would self-deadlock.
        # Order is always self.lock -> _book_lock, never the reverse.
        self._book_lock = lockwitness.make_lock("supervisor.book")
        self._consecutive: Dict[int, int] = {}
        self._last_start: Dict[int, float] = {}
        self._respawn_at: Dict[int, float] = {}
        # indices retired ON PURPOSE (autoscaler drain): their exit is the
        # goal, not a crash — never respawned
        self._retired: set = set()
        self.restarts_total = 0
        self.crash_loops_backing_off = 0

    # ------------------------------------------------------------------ #

    def _mark_down(self, index: int) -> None:
        if self.proxy is None:
            return
        try:
            replica = self.proxy.replicas[index]
        except IndexError:
            return
        if replica.alive:
            replica.alive = False
            logger.warning("supervisor: replica %d exited; removed from "
                           "rotation pending restart", index)

    def _tick(self) -> None:
        # crash bookkeeping (consecutive counts, respawn stamps, the
        # retired set) is shared with the autoscaler thread (track /
        # retire) and statusz readers (stats) — every touch goes through
        # _book_lock (DKS-C001); the proxy/log/flightrec side effects and
        # the spawn itself run after release so the lock never brackets
        # I/O or process creation (DKS-C004)
        now = time.monotonic()
        for i, proc in enumerate(list(self.procs)):
            if proc is None or proc.poll() is None:
                continue
            backoff_event = None
            respawn_due = False
            with self._book_lock:
                if i in self._retired:
                    continue  # drained on purpose: its exit is the goal
                due = self._respawn_at.get(i)
                if due is None:
                    lived = now - self._last_start.get(i, 0.0)
                    if lived >= self.policy.healthy_reset_s:
                        self._consecutive[i] = 1
                    else:
                        self._consecutive[i] = \
                            self._consecutive.get(i, 0) + 1
                    delay = self.policy.delay(self._consecutive[i])
                    self._respawn_at[i] = now + delay
                    if self._consecutive[i] > 1:
                        self.crash_loops_backing_off += 1
                    backoff_event = (proc.returncode,
                                     self._consecutive[i], delay)
                elif now >= due:
                    respawn_due = True
            # dead: the proxy must stop routing to the corpse NOW — the
            # prober only recovers, the supervisor (and failed connects)
            # declare death.  Idempotent, so re-marking each tick while
            # the backoff runs down is fine.
            self._mark_down(i)
            if backoff_event is not None:
                returncode, consecutive, delay = backoff_event
                logger.warning(
                    "supervisor: replica %d exited rc=%s (consecutive "
                    "crash #%d); restarting in %.2fs",
                    i, returncode, consecutive, delay)
                flightrec().record("replica_exit", replica=i,
                                   returncode=returncode,
                                   consecutive_crashes=consecutive,
                                   restart_in_s=round(delay, 3))
                continue
            if not respawn_due:
                continue
            with self.lock:
                if self._stop.is_set():
                    return  # shutdown won the race: never respawn
                with self._book_lock:
                    if i in self._retired:
                        continue  # retire won the race mid-backoff
                # the spawn (process creation, hundreds of ms) runs under
                # self.lock ONLY — stats()/is_retired() must not stall
                # behind it.  A retire landing in this window is the same
                # pre-existing race as retire-after-respawn: the next
                # tick sees the slot retired and never respawns again.
                self.procs[i] = self.spawn(i)
                with self._book_lock:
                    self._last_start[i] = time.monotonic()
                    self._respawn_at.pop(i, None)
                    self.restarts_total += 1
                    restarts = self.restarts_total
            logger.info("supervisor: replica %d respawned "
                        "(restart #%d)", i, restarts)
            flightrec().record("replica_restart", replica=i,
                               restarts_total=restarts)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._tick()
            except Exception:
                # the supervisor dying silently would turn every later
                # crash into a permanent outage — log and keep running
                logger.exception("supervisor tick failed")

    # ------------------------------------------------------------------ #

    def start(self) -> "ReplicaSupervisor":
        now = time.monotonic()
        with self._book_lock:
            for i in range(len(self.procs)):
                self._last_start[i] = now
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop restarting.  The owner then sweeps/terminates the procs
        under :attr:`lock`, which this stop flag guarantees no respawn
        can interleave with."""

        self._stop.set()

    # -- elastic membership (autoscaler) -------------------------------- #

    def track(self, index: int) -> None:
        """Put a freshly spawned worker at ``procs[index]`` under
        supervision: stamp its start time (a scaler-spawned worker must
        earn ``healthy_reset_s`` like any other incarnation) and clear
        any retirement left over from a previously drained slot being
        reused."""

        with self._book_lock:
            self._last_start[index] = time.monotonic()
            self._consecutive.pop(index, None)
            self._respawn_at.pop(index, None)
            self._retired.discard(index)

    def retire(self, index: int) -> None:
        """Mark one replica as retired ON PURPOSE (the autoscaler's
        drain-based scale-down): its upcoming process exit is the desired
        outcome, so the crash-restart loop must skip it.  Distinct from
        :meth:`stop`, which ends supervision fleet-wide."""

        with self._book_lock:
            self._retired.add(index)
            self._respawn_at.pop(index, None)

    def is_retired(self, index: int) -> bool:
        with self._book_lock:
            return index in self._retired

    def stats(self) -> Dict[str, int]:
        with self._book_lock:
            return {
                "restarts_total": self.restarts_total,
                "crash_loops_backing_off": self.crash_loops_backing_off,
                "retired": len(self._retired)}
