"""Fault injection and fault tolerance for the serving + pool layers.

The reference inherited its fault story from Ray (actor restarts, Serve
replica respawn, object-store lineage).  The jax_graft port replaced Ray
with hand-rolled HTTP replicas and an in-process sharded pool, so every
piece of that story has to be rebuilt explicitly:

* :mod:`~distributedkernelshap_tpu.resilience.faults` — a deterministic,
  seedable fault-injection harness (crash / hang / slow / connection drop /
  corrupt payload) wired into the REAL serving and pool code paths via
  environment or constructor hooks, so chaos tests exercise production
  failure handling rather than mocks;
* :mod:`~distributedkernelshap_tpu.resilience.supervisor` — replica
  process supervision with crash-loop exponential backoff + jitter,
  feeding liveness into the fan-in proxy;
* :mod:`~distributedkernelshap_tpu.resilience.journal` — shard-granular
  checkpoint/resume for long batch runs, keyed by the scheduling layer's
  model fingerprint (fingerprint change ⇒ journal ignored);
* :mod:`~distributedkernelshap_tpu.resilience.hedging` — tail-latency
  request hedging with per-class streaming quantile tracking.

See ``docs/RESILIENCE.md`` for the failure model and knob reference.
"""

from distributedkernelshap_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    from_env,
    parse_faults,
)
from distributedkernelshap_tpu.resilience.hedging import (  # noqa: F401
    HedgePolicy,
    LatencyQuantiles,
)
from distributedkernelshap_tpu.resilience.journal import (  # noqa: F401
    ShardJournal,
    journal_fingerprint,
)
from distributedkernelshap_tpu.resilience.supervisor import (  # noqa: F401
    ReplicaSupervisor,
    RestartPolicy,
)
