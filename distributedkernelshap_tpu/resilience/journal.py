"""Shard-granular checkpoint/resume for long batch explain runs.

A 2560-instance pool run is a sequence of independent sharded device
calls (``parallel/distributed.py`` slabs).  The reference could lean on
Ray's object-store lineage to survive a dead worker; here a killed run
would recompute everything from scratch.  This journal makes the slab
loop restartable: every completed shard's fetched result is appended to
an on-disk journal, and a resumed run replays journaled shards from disk
— bit-identical, since the stored bytes are the exact fetched arrays —
recomputing only shards that had not durably completed.

Format: JSON lines.  Line 1 is a header carrying the format magic and
the *run key* ingredients (model fingerprint, input digest, shard
layout); subsequent lines are ``{"index", "digest", "payload"}`` records
with the shard's result tuple as a base64 ``.npz`` (``allow_pickle``
off).  Appends are flushed and fsynced before the shard is considered
complete, so a crash loses at most the shard in flight.

Invalidation contract: the journal is keyed by the scheduling layer's
model fingerprint (plus the input digest and shard layout).  ANY
mismatch — refit on new background, different grouping, different
nsamples, different input batch — means the header does not match and
the journal is ignored and restarted, never partially reused.  A record
that fails its digest or decode (torn final write) is dropped; a torn
record therefore degrades to "recompute that shard", not corruption.
"""

import base64
import hashlib
import io
import json
import logging
import os
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.scheduling.result_cache import (
    array_fingerprint,
)

logger = logging.getLogger(__name__)

FORMAT = "dks-shard-journal-v1"


def _normalise(value):
    """Map a value onto restart-stable hashable content: device arrays
    become numpy (content, not repr — numpy elides large middles and
    device reprs carry addresses), callables/objects collapse to their
    qualified type name.  Collisions from the type-name fallback can only
    happen between objects whose entire parameter content already hashed
    equal; callers with predictors whose parameters live outside plain
    array attributes should pin ``distributed_opts['journal_fingerprint']``
    instead (documented in ``docs/RESILIENCE.md``)."""

    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if isinstance(value, np.ndarray):
        return value
    if hasattr(value, "shape") and hasattr(value, "dtype") \
            and hasattr(value, "__array__"):
        return np.asarray(value)
    if isinstance(value, (list, tuple)):
        return [_normalise(v) for v in value]
    if isinstance(value, dict):
        return {repr(k): _normalise(v) for k, v in value.items()}
    if callable(value):
        return f"callable:{getattr(value, '__qualname__', type(value).__name__)}"
    return f"obj:{type(value).__qualname__}"


def _update(h, value) -> None:
    value = _normalise(value)
    if isinstance(value, np.ndarray):
        h.update(b"nd:")
        h.update(array_fingerprint(value).encode())
    elif isinstance(value, list):
        h.update(f"seq{len(value)}:".encode())
        for item in value:
            _update(h, item)
    elif isinstance(value, dict):
        h.update(f"map{len(value)}:".encode())
        for key in sorted(value):
            h.update(key.encode())
            _update(h, value[key])
    else:
        h.update(repr(value).encode())


def journal_fingerprint(engine, extra: Optional[dict] = None) -> str:
    """Restart-stable fingerprint of a fitted explainer engine.

    The scheduling layer's :func:`model_fingerprint` is in-process (its
    predictor-identity fallback is ``id(predictor)``, which changes every
    restart — correct for a serving cache, useless for resume).  This
    variant hashes the predictor by CONTENT: class qualname plus the
    structured hash of its attribute dict (arrays by bytes, callables by
    qualname), alongside the same background / weights / link / seed /
    groups ingredients.  An engine (or wrapper) may pin its own
    ``fingerprint`` attribute — e.g. a checkpoint-weights hash — which
    then wins outright, mirroring ``model_fingerprint``.
    """

    explicit = getattr(engine, "fingerprint", None)
    if isinstance(explicit, str) and explicit:
        return explicit
    h = hashlib.sha256()
    background = getattr(engine, "background", None)
    if background is not None:
        h.update(array_fingerprint(np.asarray(background)).encode())
    bg_weights = getattr(engine, "bg_weights", None)
    if bg_weights is not None:
        h.update(array_fingerprint(np.asarray(bg_weights)).encode())
    config = getattr(engine, "config", None)
    h.update(repr(getattr(config, "link", None)).encode())
    h.update(repr(getattr(config, "seed",
                          getattr(engine, "seed", None))).encode())
    _update(h, getattr(engine, "groups", None))
    predictor = getattr(engine, "predictor", None)
    h.update(type(predictor).__qualname__.encode())
    _update(h, dict(getattr(predictor, "__dict__", {}) or {}))
    _update(h, extra or {})
    return h.hexdigest()


def _encode_arrays(arrays: Sequence[np.ndarray]) -> Tuple[str, str]:
    """(base64 npz, sha256 of the raw npz bytes)."""

    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": np.asarray(a) for i, a in enumerate(arrays)})
    raw = buf.getvalue()
    return (base64.b64encode(raw).decode("ascii"),
            hashlib.sha256(raw).hexdigest())


def _decode_arrays(payload: str, digest: str) -> Optional[Tuple[np.ndarray, ...]]:
    try:
        raw = base64.b64decode(payload.encode("ascii"), validate=True)
    except (ValueError, TypeError):
        return None
    if hashlib.sha256(raw).hexdigest() != digest:
        return None
    try:
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            return tuple(z[f"a{i}"] for i in range(len(z.files)))
    except (KeyError, ValueError, OSError):
        return None


class ShardJournal:
    """Append-only journal of completed shard results for ONE run.

    ``meta`` identifies the run (model fingerprint, input digest, shard
    count, explain options); an existing file whose header does not match
    byte-for-byte is discarded and restarted — the invalidation contract.
    ``put`` is durable (flush + fsync) before it returns, so a recorded
    shard survives any crash after it.  Thread-safe: fetch threads from
    the bounded pipeline append concurrently.
    """

    def __init__(self, path: str, meta: Dict[str, Any]):
        self.path = path
        self.meta = {"format": FORMAT, **meta}
        self._lock = threading.Lock()
        # decoded resume data, held only until get() hands it out; _done
        # tracks completion for BOTH restored and freshly put shards so a
        # fresh put never keeps a second in-memory copy of its result
        # (the pipeline's own results list already holds it)
        self._entries: Dict[int, Tuple[np.ndarray, ...]] = {}
        self._done: set = set()
        self.restored = 0       # shards replayed from disk this run
        self.computed = 0       # shards recorded fresh this run
        self._load()
        self._fh = open(self.path, "a", encoding="ascii")

    def _load(self) -> None:
        if not os.path.exists(self.path):
            self._write_header()
            return
        try:
            with open(self.path, "r", encoding="ascii") as fh:
                lines = fh.read().splitlines()
        except OSError:
            logger.warning("unreadable shard journal %s; restarting it",
                           self.path)
            self._write_header()
            return
        header = None
        if lines:
            try:
                header = json.loads(lines[0])
            except ValueError:
                pass
        if header != self.meta:
            if lines:
                logger.warning(
                    "shard journal %s belongs to a different run "
                    "(fingerprint/input/layout changed); ignoring it",
                    self.path)
                # invalidations are exactly what a resume post-mortem
                # needs on the flight-recorder timeline: "why did this
                # run recompute everything?"
                flightrec().record("journal_invalidated", path=self.path,
                                   records=max(0, len(lines) - 1))
            self._write_header()
            return
        for line in lines[1:]:
            try:
                rec = json.loads(line)
                index = int(rec["index"])
                arrays = _decode_arrays(rec["payload"], rec["digest"])
            except (ValueError, KeyError, TypeError):
                arrays = None
            if arrays is None:
                # torn tail write (the crash landed mid-append): that
                # shard simply recomputes
                logger.warning("dropping undecodable record in %s",
                               self.path)
                flightrec().record("journal_torn_record", path=self.path)
                continue
            self._entries[index] = arrays
            self._done.add(index)
        if self._entries:
            logger.info("shard journal %s: resuming with %d completed "
                        "shard(s)", self.path, len(self._entries))
            flightrec().record("journal_resume", path=self.path,
                               restored_shards=len(self._entries))

    def _write_header(self) -> None:
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(self.path, "w", encoding="ascii") as fh:
            fh.write(json.dumps(self.meta, sort_keys=False) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        self._entries = {}
        self._done = set()

    # ------------------------------------------------------------------ #

    def get(self, index: int) -> Optional[Tuple[np.ndarray, ...]]:
        with self._lock:
            # pop: once handed to the caller (the pipeline's results
            # list) the journal's copy is redundant host memory
            arrays = self._entries.pop(index, None)
            if arrays is not None:
                self.restored += 1
            return arrays

    def put(self, index: int, arrays: Sequence[np.ndarray]) -> None:
        payload, digest = _encode_arrays(arrays)
        line = json.dumps({"index": int(index), "digest": digest,
                           "payload": payload}) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._done.add(int(index))
            self.computed += 1

    @property
    def completed(self) -> int:
        with self._lock:
            return len(self._done)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"path": self.path, "completed": len(self._done),
                    "restored": self.restored, "computed": self.computed}

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def run_journal_path(checkpoint_dir: str, fingerprint: str,
                     input_digest: str) -> str:
    """Content-addressed journal filename: the same (model, input, opts)
    resumes the same file; anything else gets a fresh one."""

    key = hashlib.sha256(f"{fingerprint}:{input_digest}".encode()).hexdigest()
    return os.path.join(checkpoint_dir, f"shards-{key[:24]}.journal")
