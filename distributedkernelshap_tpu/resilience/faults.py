"""Deterministic, seedable fault injection for the serving and pool paths.

Chaos tests are only worth their runtime if they drive the REAL failure
handling — the proxy's 502/504 paths, the supervisor's restart loop, the
journal's resume — so faults are injected at named *sites* inside the
production code (``serving/server.py``, ``parallel/pipeline.py``) rather
than by mocking the components around them.  Every fault is deterministic
given its spec: triggers are hit-counted (``after=N`` skips the first N
hits at the site) and probabilistic triggers draw from a spec-owned
``random.Random(seed)``, so a chaos scenario replays identically.

Spec grammar (``DKS_FAULTS`` env var, ``;``-separated)::

    kind:site=SITE[,after=N][,times=M][,p=F][,seed=S][,delay=SECONDS]
        [,replica=K]

with ``kind`` one of:

``crash``
    ``os._exit(42)`` — the process dies mid-request exactly like a
    SIGKILLed replica (no atexit, no flush).
``hang``
    sleep ``delay`` seconds (default 3600) — a wedged device relay: the
    socket stays open, nothing answers, only timeouts/watchdogs fire.
``slow``
    sleep ``delay`` seconds (default 0.5) then continue — a straggler.
``drop``
    returned to the caller, which closes the connection without replying
    (mid-request connection loss as seen by the client/proxy).
``corrupt``
    returned to the caller, which garbles the response payload before
    sending (bit-rot / truncated-write on the wire).

``after=N``
    skip the first N hits at the site; fire from hit N+1 on.
``times=M``
    fire at most M times (default unlimited).
``p=F``
    once armed, fire with probability F per hit (seeded; default 1.0).
``replica=K``
    only active in the worker whose ``DKS_REPLICA_INDEX`` env equals K —
    one fleet-wide ``DKS_FAULTS`` value can script per-replica behaviour.

Sites currently consulted:

``server.accept``
    ``ExplainerServer``'s handler, after the body parses and before
    admission (crash/hang/slow before any device work).
``server.explain``
    just before the success response is sent (crash/hang/slow/drop/
    corrupt after the device computed — the worst case for lost work).
``pool.shard``
    ``parallel/pipeline.run_pipeline`` on JOURNALED slab loops only,
    after a shard's fetch completes and BEFORE it is journaled —
    ``crash:site=pool.shard,after=K`` kills a batch run with exactly one
    fetched-but-unjournaled shard, the shard a resume must recompute.
    Non-journaled pipelines (the engine's internal chunk loops, serving)
    never consult it, so the hit count stays a pure shard counter.
``scaler.tick``
    ``serving/autoscaler.Autoscaler``'s control loop, at the top of each
    evaluation tick.  The scaler is a control thread inside the fleet's
    parent process, so ``crash`` here is THREAD-scoped (the scaler calls
    :meth:`FaultInjector.fire` with ``crash_scope="thread"``): the fired
    crash is returned to the caller, which kills the scaler loop and
    nothing else — a whole-process ``os._exit`` would take the fan-in
    proxy and every client connection with it, which is a different
    fault (process death) the other sites already script.  ``hang``
    wedges the tick thread.  Either way the fleet must degrade to its
    CURRENT size and keep serving (never drain to zero) — the invariant
    ``chaos_bench.py --check`` asserts.
``engine.phi``
    ``ExplainerServer._complete``, after the engine's answer payload is
    assigned and BEFORE the quality audit / result-cache insert.
    ``corrupt`` here is a *numeric* fault, not a wire fault: the
    cooperating call site rewrites the payload through
    :func:`corrupt_phi_payload` — the document still parses, the phi
    values inside are wrong (one attribution perturbed, seeded by the
    site's hit count).  This is the "device computed a wrong answer"
    drill the transport-level ``server.explain`` corrupt cannot script,
    and the true-positive arm of ``benchmarks/quality_bench.py
    --check``: the in-band invariant auditor must flag it.
"""

import json
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

KINDS = ("crash", "hang", "slow", "drop", "corrupt")

#: default sleep per kind when the spec carries no ``delay=``
_DEFAULT_DELAY_S = {"hang": 3600.0, "slow": 0.5}

#: exit code used by ``crash`` so tests/benchmarks can tell an injected
#: crash from an organic one
CRASH_EXIT_CODE = 42


class FaultSpec:
    """One parsed fault clause (see module doc for the grammar)."""

    def __init__(self, kind: str, site: str, after: int = 0,
                 times: Optional[int] = None, p: float = 1.0,
                 seed: int = 0, delay_s: Optional[float] = None,
                 replica: Optional[int] = None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"expected one of {KINDS}")
        if not site:
            raise ValueError("a fault spec needs site=...")
        if after < 0:
            raise ValueError("after= must be >= 0")
        if times is not None and times < 1:
            raise ValueError("times= must be >= 1")
        if not 0.0 <= p <= 1.0:
            raise ValueError("p= must be in [0, 1]")
        self.kind = kind
        self.site = site
        self.after = int(after)
        self.times = times
        self.p = float(p)
        self.seed = int(seed)
        self.delay_s = (float(delay_s) if delay_s is not None
                        else _DEFAULT_DELAY_S.get(kind, 0.0))
        self.replica = replica
        # per-spec state: hit counter and a private RNG so the fire
        # sequence is a pure function of (spec, hit order)
        self._hits = 0
        self._fired = 0
        self._rng = random.Random(self.seed)

    def __repr__(self):
        return (f"FaultSpec({self.kind}:site={self.site},after={self.after},"
                f"times={self.times},p={self.p},delay={self.delay_s},"
                f"replica={self.replica})")


def parse_faults(text: str) -> List[FaultSpec]:
    """Parse a ``DKS_FAULTS`` value into specs; raises ``ValueError`` on a
    malformed clause (a chaos run with a silently-dropped fault would
    pass for the wrong reason)."""

    specs = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, rest = clause.partition(":")
        kind = kind.strip()
        fields: Dict[str, str] = {}
        for part in rest.split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, value = part.partition("=")
            if not eq:
                raise ValueError(f"bad fault field {part!r} in {clause!r}")
            fields[key.strip()] = value.strip()
        unknown = set(fields) - {"site", "after", "times", "p", "seed",
                                 "delay", "replica"}
        if unknown:
            raise ValueError(f"unknown fault fields {sorted(unknown)} "
                             f"in {clause!r}")
        specs.append(FaultSpec(
            kind,
            fields.get("site", ""),
            after=int(fields.get("after", 0)),
            times=int(fields["times"]) if "times" in fields else None,
            p=float(fields.get("p", 1.0)),
            seed=int(fields.get("seed", 0)),
            delay_s=float(fields["delay"]) if "delay" in fields else None,
            replica=int(fields["replica"]) if "replica" in fields else None,
        ))
    return specs


class FaultInjector:
    """Evaluates fault specs at injection sites.

    ``fire(site)`` performs in-process faults (crash exits, hang/slow
    sleep) and returns the fault *kind* for faults that need caller
    cooperation (``drop``, ``corrupt``) — the call site interprets those.
    Returns ``None`` when nothing fires.  Thread-safe: hit counting is
    locked so concurrent handler threads see one global hit order (the
    order itself is scheduling-dependent under concurrency; deterministic
    scenarios use single-threaded sites or ``after=`` counts larger than
    the concurrency window).
    """

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._lock = threading.Lock()

    def _decide(self, site: str) -> Optional[FaultSpec]:
        with self._lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                spec._hits += 1
                if spec._hits <= spec.after:
                    continue
                if spec.times is not None and spec._fired >= spec.times:
                    continue
                if spec.p < 1.0 and spec._rng.random() >= spec.p:
                    continue
                spec._fired += 1
                return spec
        return None

    def fire(self, site: str, crash_scope: str = "process") -> Optional[str]:
        """Evaluate ``site``; see the class doc.  ``crash_scope`` selects
        what a fired ``crash`` kills: ``"process"`` (default — the
        historical ``os._exit``) or ``"thread"``, where ``"crash"`` is
        RETURNED and the caller owns dying — used by control loops (the
        autoscaler's ``scaler.tick``) whose death must not take the
        serving process with them."""

        spec = self._decide(site)
        if spec is None:
            return None
        logger.warning("fault injection: firing %s at site %s",
                       spec.kind, site)
        # the flight recorder is the chaos run's shared timeline: every
        # fired fault lands on it, and a crash dumps the whole ring to
        # $DKS_FLIGHTREC_DIR before the process dies — one artifact
        # instead of log archaeology.  Imported lazily: faults must parse
        # specs at worker startup before anything heavier loads.
        from distributedkernelshap_tpu.observability.flightrec import (
            flightrec,
        )

        flightrec().record("fault_injected", fault=spec.kind, site=site,
                           delay_s=spec.delay_s)
        if spec.kind == "crash":
            if crash_scope == "thread":
                # the caller kills ITS OWN loop; the process (proxy,
                # replicas, client sockets) lives on
                return spec.kind
            # the dump happens HERE because nothing after os._exit does:
            # no atexit, no flush — an injected crash is the one fault
            # that can still leave its black box behind
            flightrec().dump_crash(reason=f"injected crash at {site}")
            # os._exit, not sys.exit: a real crash skips atexit handlers,
            # response flushing, everything — that is the point
            os._exit(CRASH_EXIT_CODE)
        if spec.kind in ("hang", "slow"):
            time.sleep(spec.delay_s)
            return spec.kind
        return spec.kind  # drop / corrupt: caller cooperates

    def hits(self, site: str) -> int:
        with self._lock:
            return sum(s._hits for s in self.specs if s.site == site)


def corrupt_payload(payload: bytes) -> bytes:
    """Deterministically garble a response payload: overwrite the middle
    with bytes that cannot parse as JSON, keeping the length (so
    ``Content-Length`` framing stays intact and the corruption is a
    payload-level fault, not a framing fault)."""

    marker = b"\xffCORRUPTED\xff"
    if len(payload) <= len(marker):
        return marker[:len(payload)]
    mid = (len(payload) - len(marker)) // 2
    return payload[:mid] + marker + payload[mid + len(marker):]


def corrupt_phi_payload(payload, seed: int = 0):
    """Numerically corrupt one served explanation payload (the
    ``engine.phi`` site's cooperating rewrite): decode it, add a large
    deterministic delta to one phi entry — chosen by ``seed``, normally
    the site's hit count — and re-encode in the SAME wire format.  The
    result still parses and still frames; only the additivity invariant
    is broken, which is exactly what a silent device numeric fault looks
    like.  Payloads that cannot be decoded are returned unchanged (the
    drill needs a parsable-but-wrong answer, not a transport fault)."""

    import numpy as np

    from distributedkernelshap_tpu.serving import wire

    binary = isinstance(payload, (bytes, bytearray))
    try:
        if binary:
            arrays = wire.decode_explanation(bytes(payload))
        else:
            doc = json.loads(payload)
            arrays = wire.explanation_payload_from_json(payload)
    except Exception:  # noqa: BLE001 — leave undecodable payloads alone
        return payload
    sv = [np.array(v, dtype=np.float64)
          for v in arrays["shap_values"]]
    if not sv or not sv[0].size:
        return payload
    rng = random.Random(seed)
    k = rng.randrange(len(sv))
    flat = sv[k].reshape(-1)
    flat[rng.randrange(flat.shape[0])] += 10.0 + rng.random()
    if binary:
        return wire.encode_explanation(
            sv, np.asarray(arrays["expected_value"]),
            np.asarray(arrays["raw_prediction"]),
            interaction_values=arrays.get("interaction_values"))
    doc["data"]["shap_values"] = [v.tolist() for v in sv]
    return json.dumps(doc)


def from_env(env: Optional[Dict[str, str]] = None) -> Optional[FaultInjector]:
    """Build an injector from ``DKS_FAULTS``; ``None`` when unset/empty.

    Specs carrying ``replica=K`` are kept only when this process's
    ``DKS_REPLICA_INDEX`` matches, so one fleet-wide env value scripts
    per-replica behaviour (slow replica 2, crash replica 0, ...).
    """

    env = os.environ if env is None else env
    text = env.get("DKS_FAULTS", "").strip()
    if not text:
        return None
    specs = parse_faults(text)
    index = env.get("DKS_REPLICA_INDEX")
    kept = [s for s in specs
            if s.replica is None
            or (index is not None and int(index) == s.replica)]
    if not kept:
        return None
    logger.warning("fault injection active: %s", kept)
    return FaultInjector(kept)


_env_injector_cache: List = []  # [Optional[FaultInjector]] once resolved


def env_injector() -> Optional[FaultInjector]:
    """Process-wide injector resolved from the environment ONCE (hit
    counters must persist across call sites; re-parsing per call would
    reset them).  Tests monkeypatch this or use :func:`from_env`."""

    if not _env_injector_cache:
        _env_injector_cache.append(from_env())
    return _env_injector_cache[0]
