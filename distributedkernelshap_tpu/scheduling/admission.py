"""Admission control and backpressure for the explanation server.

Under overload the round-4 server accepted everything: every request
queued, every request eventually timed out, and clients learned about the
overload only after burning their full timeout budget.  Production
accelerator-serving stacks shed load *early* instead — a rejected request
costs microseconds and carries a ``Retry-After`` hint, so well-behaved
clients back off and the work that IS admitted finishes inside its SLO.

Three independent gates, all cheap and all host-side (never a device op):

1. **Bounded per-class queues** — each priority class has a depth bound;
   a full class rejects without touching the others (a runaway batch
   client cannot wedge interactive traffic).
2. **Per-client token buckets** — rate limiting keyed by the client key
   (``X-DKS-Client`` header, else peer address), refilled continuously.
3. **Projected-wait shedding** — an EWMA of the device's observed
   rows/second projects how long the queue ahead will take; a request
   whose *own* declared deadline would already be missed while queued is
   rejected now (HTTP 429 + ``Retry-After``) rather than dispatched late
   or timed out.  Requests without an explicit deadline are never shed by
   this gate.

Everything is injectable-clock testable and lock-protected; the server
calls :meth:`AdmissionController.admit` from HTTP handler threads.
"""

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from distributedkernelshap_tpu.analysis import lockwitness


class TokenBucket:
    """Continuous-refill token bucket (``rate`` tokens/s, ``burst`` cap)."""

    def __init__(self, rate: float, burst: float, now=time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self._now = now
        self._tokens = float(burst)
        self._t_last = now()
        self._lock = lockwitness.make_lock("admission.bucket")

    def try_acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        """Take ``n`` tokens if available.  Returns ``(acquired,
        retry_after_s)`` — on failure ``retry_after_s`` is how long until
        the bucket will have refilled enough."""

        with self._lock:
            t = self._now()
            self._tokens = min(self.burst,
                               self._tokens + (t - self._t_last) * self.rate)
            self._t_last = t
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        with self._lock:
            t = self._now()
            return min(self.burst,
                       self._tokens + (t - self._t_last) * self.rate)


class ServiceRateEstimator:
    """EWMA of observed device throughput in rows/second.

    The server feeds it one observation per completed device batch; the
    admission controller divides queued rows by it to project queue wait.
    Before any observation it reports ``None`` — the projected-wait gate
    then admits (no evidence of overload yet).

    The EWMA assumes the downstream capacity producing the observations
    is static.  When it is not — the autoscaler resized the replica
    fleet behind a fan-in, or a fleet-level estimator watches N workers —
    :meth:`capacity_hint` rescales the believed rate proportionally at
    the moment capacity changes, so projected-wait shedding neither
    over-sheds right after a scale-up (the EWMA still believing the old,
    smaller fleet) nor under-sheds after a drain (still believing the
    bigger one).  The hint moves the ESTIMATE once; subsequent
    observations keep correcting it as usual.
    """

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._rate: Optional[float] = None
        self._capacity_units: Optional[float] = None
        self._rows_total = 0
        self._lock = lockwitness.make_lock("admission.estimator")

    def observe(self, rows: int, seconds: float) -> None:
        if seconds <= 0 or rows <= 0:
            return
        sample = rows / seconds
        with self._lock:
            self._rows_total += int(rows)
            self._rate = (sample if self._rate is None
                          else self.alpha * sample
                          + (1.0 - self.alpha) * self._rate)

    def capacity_hint(self, units: float) -> None:
        """Declare the downstream capacity in arbitrary ``units``
        (typically ready replicas).  The first call only records the
        baseline; later calls rescale the current EWMA by the units
        ratio.  Called by the autoscaler on every completed scale event
        and by ``ReplicaManager`` with the starting fleet size."""

        units = float(units)
        if units <= 0:
            raise ValueError("capacity_hint units must be positive")
        with self._lock:
            if self._capacity_units and self._rate is not None:
                self._rate *= units / self._capacity_units
            self._capacity_units = units

    def rows_observed_total(self) -> int:
        """Cumulative rows fed through :meth:`observe` — a monotonic
        served-rows counter.  A fleet-level consumer (the autoscaler)
        differentiates it across polls to get a rows/s DEMAND that is
        unit-compatible with :meth:`rows_per_s` capacity, which a
        request-count rate is not (requests carry arbitrary row counts)."""

        with self._lock:
            return self._rows_total

    def rows_per_s(self) -> Optional[float]:
        with self._lock:
            return self._rate


class AdmissionDecision:
    __slots__ = ("admitted", "reason", "retry_after_s")

    def __init__(self, admitted: bool, reason: str = "",
                 retry_after_s: float = 0.0):
        self.admitted = admitted
        self.reason = reason  # "queue_full" | "rate_limited" | "projected_wait"
        self.retry_after_s = retry_after_s

    def __bool__(self) -> bool:
        return self.admitted


class AdmissionController:
    """Combines the three gates; see module docstring.

    Parameters
    ----------
    max_queued_per_class
        Depth bound applied per priority class (int for a uniform bound,
        or a ``{class: bound}`` dict — classes missing from the dict keep
        the default bound of 1024; an explicit 0 entry disables the gate
        for that class).  ``None``/0 disables the gate everywhere.
    rate_limit_per_client
        ``(rate_per_s, burst)`` for the per-client token buckets, counted
        in requests.  ``None`` disables rate limiting.
    estimator
        Shared :class:`ServiceRateEstimator` (the server owns it and feeds
        completions); ``None`` disables projected-wait shedding.
    max_client_buckets
        Bound on tracked client keys so an adversarial key-space cannot
        grow memory without bound; least-recently-seen keys are evicted
        (their next request simply starts a fresh, full bucket).
    """

    def __init__(self,
                 max_queued_per_class=1024,
                 rate_limit_per_client: Optional[Tuple[float, float]] = None,
                 estimator: Optional[ServiceRateEstimator] = None,
                 max_client_buckets: int = 10_000,
                 now=time.monotonic):
        if isinstance(max_queued_per_class, dict):
            self._bounds = dict(max_queued_per_class)
            # unlisted classes keep a real bound: a {class: N} override
            # must not silently unbound every OTHER class's queue
            self._default_bound = 1024
        else:
            self._bounds = {}
            self._default_bound = int(max_queued_per_class or 0)
        self.rate_limit_per_client = rate_limit_per_client
        self.estimator = estimator
        self.max_client_buckets = int(max_client_buckets)
        self._now = now
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._buckets_lock = lockwitness.make_lock("admission.clients")

    def _bound_for(self, klass: str) -> int:
        return int(self._bounds.get(klass, self._default_bound) or 0)

    def capacity_hint(self, units: float) -> None:
        """Forward a downstream-capacity change to the estimator (no-op
        without one) — see :meth:`ServiceRateEstimator.capacity_hint`."""

        if self.estimator is not None:
            self.estimator.capacity_hint(units)

    def _bucket_for(self, client_key: str) -> TokenBucket:
        rate, burst = self.rate_limit_per_client
        with self._buckets_lock:
            bucket = self._buckets.get(client_key)
            if bucket is None:
                bucket = TokenBucket(rate, burst, now=self._now)
                self._buckets[client_key] = bucket
                while len(self._buckets) > self.max_client_buckets:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(client_key)
            return bucket

    def admit(self, klass: str, rows: int, client_key: str,
              deadline: Optional[float] = None,
              queue_depth: int = 0,
              queued_rows: int = 0) -> AdmissionDecision:
        """Decide one request.  ``deadline`` is absolute monotonic seconds
        (or ``None``); ``queue_depth`` is the request's class depth and
        ``queued_rows`` the total rows queued ahead of it (both read from
        the scheduler by the caller)."""

        bound = self._bound_for(klass)
        if bound and queue_depth >= bound:
            rps = self.estimator.rows_per_s() if self.estimator else None
            retry = (queued_rows / rps) if (rps and queued_rows) else 1.0
            return AdmissionDecision(False, "queue_full",
                                     max(0.1, min(retry, 60.0)))
        if deadline is not None and self.estimator is not None:
            rps = self.estimator.rows_per_s()
            if rps:
                projected_wait = (queued_rows + rows) / rps
                if self._now() + projected_wait > deadline:
                    return AdmissionDecision(False, "projected_wait",
                                             max(0.1, min(projected_wait,
                                                          60.0)))
        # token consumption LAST: the side-effect-free gates above must not
        # charge a client's bucket for a request that is then rejected
        # anyway (retries after a projected_wait 429 would find the bucket
        # drained by the rejected attempts themselves)
        if self.rate_limit_per_client is not None:
            ok, retry = self._bucket_for(client_key).try_acquire(1.0)
            if not ok:
                return AdmissionDecision(False, "rate_limited",
                                         max(0.05, retry))
        return AdmissionDecision(True)
