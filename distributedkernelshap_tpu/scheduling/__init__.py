from distributedkernelshap_tpu.scheduling.scheduler import (  # noqa: F401
    DEFAULT_CLASS_BUDGETS_S,
    PRIORITY_CLASSES,
    FIFOScheduler,
    SLOScheduler,
    StagingBuffer,
    make_scheduler,
)
from distributedkernelshap_tpu.scheduling.admission import (  # noqa: F401
    AdmissionController,
    AdmissionDecision,
    ServiceRateEstimator,
    TokenBucket,
)
from distributedkernelshap_tpu.scheduling.result_cache import (  # noqa: F401
    ResultCache,
    array_fingerprint,
    model_fingerprint,
    request_cache_key,
)
