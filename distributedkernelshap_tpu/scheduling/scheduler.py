"""SLO-aware continuous-batching scheduler.

Replaces :class:`ExplainerServer`'s FIFO ``queue.Queue`` + ``_fill_batch``
poll loop (``serving/server.py``, rounds 1-5).  The FIFO had three
production-scale problems the ROADMAP north star ("heavy traffic from
millions of users") runs straight into:

* **No priorities or deadlines** — a 1-row interactive request parks behind
  a 2000-row batch job; under overload every request waits and then the
  whole queue times out together.
* **Idle polling** — the dispatcher woke every 0.1 s to check for work, so
  a lone request paid up to 100 ms of scheduling latency before the device
  ever saw it.
* **A one-slot carry** — a request deferred because it would overflow the
  model's ``max_rows`` broadcast slot lived in a side variable the watchdog
  drain could not see.

This scheduler keeps every queued request in ONE earliest-deadline-first
heap.  Each request carries a priority class (``interactive`` / ``batch`` /
``best_effort``) and an optional absolute deadline; requests without an
explicit deadline are ordered by ``enqueue_time + class budget``, so under
contention interactive traffic sorts ahead of batch traffic *by
construction* rather than via separate queues that need cross-queue
starvation rules.  Batch formation pops in EDF order and packs rows up to
the model's ``max_rows`` budget; an item that would overflow is pushed back
into the heap with its original key, where the advancing clock makes it the
earliest item — it leads the next batch, so deferral can never starve it.
Wakeups are condition-variable driven: ``put`` notifies the dispatcher, so
an idle server dispatches a lone request immediately instead of on the next
poll tick.

The gemma-on-TPU serving comparison and Podracer's centralized batcher
(PAPERS.md) both locate exactly this layer — batch formation by deadline
and cost — as where accelerator serving throughput comes from.
"""

import heapq
import logging
import queue
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from distributedkernelshap_tpu.analysis import lockwitness

logger = logging.getLogger(__name__)

PRIORITY_CLASSES = ("interactive", "batch", "best_effort")

# Ordering budgets (seconds): a request with no explicit deadline is
# scheduled as if it were due ``enqueue + budget[class]``.  These are
# *ordering* knobs only — nothing is shed for missing an implicit budget;
# shedding applies solely to requests that declared a real deadline.
DEFAULT_CLASS_BUDGETS_S: Dict[str, float] = {
    "interactive": 0.5,
    "batch": 30.0,
    "best_effort": 120.0,
}

# queue-wait histogram bounds (seconds): finer than the request-latency
# buckets at the low end — queue wait is the scheduler's own contribution
# to latency and the interactive budget is 0.5 s
QUEUE_WAIT_BUCKETS_S = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0, 30.0)


class SLOScheduler:
    """EDF request queue with row-budget batch formation.

    Items must expose ``klass`` (one of :data:`PRIORITY_CLASSES`),
    ``deadline`` (absolute ``time.monotonic`` seconds, or ``None``),
    ``t_enqueued`` (monotonic), ``rows`` (int) and ``done`` (bool — set by
    whoever answers the request out-of-band, e.g. the server's wedge path;
    done items are dropped, not dispatched).

    Only one consumer thread may call :meth:`next_batch` (the server runs
    one dispatcher); any number of producers may :meth:`put`.
    """

    def __init__(self, class_budgets: Optional[Dict[str, float]] = None,
                 now=time.monotonic):
        self._budgets = dict(DEFAULT_CLASS_BUDGETS_S)
        if class_budgets:
            self._budgets.update(class_budgets)
        self._now = now
        # named for the runtime lock-order witness (DKS_LOCK_WITNESS)
        self._cond = lockwitness.make_condition("scheduler.cond")
        self._heap: List[Tuple[float, int, object]] = []
        self._seq = 0
        self._depths: Dict[str, int] = {k: 0 for k in PRIORITY_CLASSES}
        self._queued_rows = 0
        self._stopped = False
        # deficit-round-robin state for tenant-aware packing (grouped
        # next_batch only): group key -> row deficit.  A group passed over
        # this cycle keeps its credit and leads a later one, so a
        # high-rate tenant can never starve another tenant's batch past
        # its share.  Bounded (LRU) — keys are tenant/share identities.
        self._drr: "OrderedDict[object, float]" = OrderedDict()
        # observability (attach_metrics): None until the owner attaches a
        # registry — the scheduler is also used standalone in unit tests
        self._m_enqueued = None
        self._m_queue_wait = None
        self._m_expired = None
        self._m_pushbacks = None
        self._m_requeues = None

    def attach_metrics(self, registry) -> None:
        """Register this scheduler's ``dks_sched_*`` series on the
        owner's :class:`~distributedkernelshap_tpu.observability.metrics.
        MetricsRegistry` — the server calls this so queue behaviour
        (wait, expiries, packing pushback) renders on the same ``/metrics``
        page as the serving counters.  Queue DEPTH stays the server-owned
        ``dks_serve_queue_depth`` gauge (pre-existing name, preserved)."""

        self._m_enqueued = registry.counter(
            "dks_sched_enqueued_total",
            "Requests accepted into the scheduler queue.",
            labelnames=("class",)).seed(*[(k,) for k in PRIORITY_CLASSES])
        self._m_queue_wait = registry.histogram(
            "dks_sched_queue_wait_seconds",
            "Time from enqueue to batch claim.",
            buckets=QUEUE_WAIT_BUCKETS_S, labelnames=("class",))
        self._m_expired = registry.counter(
            "dks_sched_expired_total",
            "Requests whose explicit deadline passed while queued.",
            labelnames=("class",)).seed(*[(k,) for k in PRIORITY_CLASSES])
        self._m_pushbacks = registry.counter(
            "dks_sched_row_budget_pushbacks_total",
            "Items deferred to a later batch by packing: the row budget, "
            "or — under tenant-aware grouped formation — bucket-boundary "
            "trims, deficit-round-robin displacement and quota-yield "
            "caps (routine under healthy multi-tenant load, not a "
            "pressure signal there).")
        self._m_requeues = registry.counter(
            "dks_sched_requeues_total",
            "Partially-served requests re-entered into the queue at a "
            "preemption point (anytime refinement round boundaries): each "
            "re-entry competes under EDF again, so an earlier-deadline "
            "arrival preempts further refinement.",
            labelnames=("class",)).seed(*[(k,) for k in PRIORITY_CLASSES])

    # -- ordering hooks (FIFOScheduler overrides) ----------------------- #

    def _effective_deadline(self, item) -> float:
        if getattr(item, "deadline", None) is not None:
            return item.deadline
        budget = self._budgets.get(getattr(item, "klass", "batch"),
                                   self._budgets["batch"])
        return item.t_enqueued + budget

    def _is_expired(self, item, now: float) -> bool:
        deadline = getattr(item, "deadline", None)
        return deadline is not None and now > deadline

    # -- producer side -------------------------------------------------- #

    def put(self, item) -> None:
        with self._cond:
            heapq.heappush(self._heap,
                           (self._effective_deadline(item), self._seq, item))
            self._seq += 1
            klass = getattr(item, "klass", "batch")
            self._depths[klass] = self._depths.get(klass, 0) + 1
            self._queued_rows += item.rows
            self._cond.notify()
        if self._m_enqueued is not None:
            self._m_enqueued.inc(**{"class": klass})

    def requeue(self, item) -> None:
        """Re-enter a partially-served request at a preemption point
        (anytime round boundary).  Ordering is plain EDF — the item's
        deadline has not changed, so it resumes ahead of later-deadline
        work but yields to anything more urgent that arrived while its
        last round ran.  Counted separately from fresh enqueues
        (``dks_sched_requeues_total``) so queue-depth arithmetic against
        ``dks_sched_enqueued_total`` stays honest."""

        with self._cond:
            heapq.heappush(self._heap,
                           (self._effective_deadline(item), self._seq, item))
            self._seq += 1
            klass = getattr(item, "klass", "batch")
            self._depths[klass] = self._depths.get(klass, 0) + 1
            self._queued_rows += item.rows
            self._cond.notify()
        if self._m_requeues is not None:
            self._m_requeues.inc(**{"class": klass})

    # -- introspection (admission control, metrics) --------------------- #

    def depths(self) -> Dict[str, int]:
        with self._cond:
            return dict(self._depths)

    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def rows_ahead(self, klass: str, deadline: Optional[float]) -> int:
        """Rows queued that would sort AHEAD of a hypothetical request of
        ``klass`` with ``deadline`` (absolute monotonic, or ``None`` for
        the class budget) — the EDF-aware input to admission's
        projected-wait gate.  Dividing the TOTAL queue by the service rate
        would project as if the request waited behind every queued row,
        i.e. it would shed exactly the interactive traffic this scheduler
        dispatches first.  On :class:`FIFOScheduler` every stored key is
        0.0, so this degrades to the whole queue — correct for FIFO, where
        everything really is ahead."""

        if deadline is None:
            deadline = self._now() + self._budgets.get(
                klass, self._budgets["batch"])
        with self._cond:
            return sum(item.rows for eff, _, item in self._heap
                       if eff <= deadline and not getattr(item, "done",
                                                          False))

    def qsize(self) -> int:
        with self._cond:
            return len(self._heap)

    # -- consumer side --------------------------------------------------- #

    def _account_pop(self, item) -> None:
        klass = getattr(item, "klass", "batch")
        self._depths[klass] = max(0, self._depths.get(klass, 0) - 1)
        self._queued_rows = max(0, self._queued_rows - item.rows)

    def next_batch(self, max_batch_size: int, max_rows: Optional[int] = None,
                   batch_timeout_s: float = 0.0,
                   stop: Optional[threading.Event] = None,
                   idle_wait_s: float = 0.5, grouping=None):
        """Form one batch.  Returns ``(batch, expired)``.

        Blocks (condition-variable wait, bounded by ``idle_wait_s`` per
        sleep so ``stop`` is honoured) until a request arrives, then keeps
        packing in EDF order — waking on new arrivals — until the batch is
        full, the row budget is met, or ``batch_timeout_s`` has passed
        since the first pop.  ``expired`` holds popped items whose explicit
        deadline had already passed: the caller owns failing them (they
        must not cost device work).  Returns ``(None, [])`` when stopped
        while idle.

        ``grouping`` (None = the historical tenant-blind formation) is a
        policy object with ``key(item)`` / ``bucket(key, rows)`` /
        ``limit(key)`` — see :meth:`_fill_grouped` — that turns formation
        tenant-aware: items of one group are packed contiguously up to a
        compile-bucket boundary before another group's items are admitted,
        under deficit-round-robin fairness across groups.
        """

        with self._cond:
            while not self._heap:
                if self._stopped or (stop is not None and stop.is_set()):
                    return None, []
                self._cond.wait(timeout=idle_wait_s)
            if grouping is not None:
                return self._fill_grouped(max_batch_size, max_rows,
                                          batch_timeout_s, stop, grouping)
            batch: List[object] = []
            expired: List[object] = []
            counted_pushback: set = set()
            rows = 0
            fill_deadline = self._now() + (batch_timeout_s
                                           if max_batch_size > 1 else 0.0)
            while True:
                pushback: List[Tuple[float, int, object]] = []
                now = self._now()
                while self._heap and len(batch) < max_batch_size:
                    if max_rows and rows >= max_rows:
                        # budget exactly consumed: nothing can fit, so
                        # don't churn the rest of the heap through the
                        # pushback path (O(n log n) per batch under
                        # backlog, all while holding the lock)
                        break
                    eff, seq, item = heapq.heappop(self._heap)
                    if getattr(item, "done", False):
                        self._account_pop(item)
                        continue
                    if self._is_expired(item, now):
                        self._account_pop(item)
                        expired.append(item)
                        if self._m_expired is not None:
                            self._m_expired.inc(**{
                                "class": getattr(item, "klass", "batch")})
                        continue
                    if batch and max_rows and rows + item.rows > max_rows:
                        # row-budget packing: keep scanning for items that
                        # still fit; the overflow item keeps its original
                        # key, so it leads a subsequent batch (no
                        # starvation, no side-channel carry slot)
                        pushback.append((eff, seq, item))
                        continue
                    self._account_pop(item)
                    if self._m_queue_wait is not None:
                        self._m_queue_wait.observe(
                            max(0.0, now - item.t_enqueued),
                            **{"class": getattr(item, "klass", "batch")})
                    batch.append(item)
                    rows += item.rows
                for entry in pushback:
                    heapq.heappush(self._heap, entry)
                if pushback and self._m_pushbacks is not None:
                    # once per item per next_batch call: the inner loop
                    # rescans the heap on every wakeup before the fill
                    # deadline, and re-counting the same deferred item per
                    # scan would overstate pushback by the wakeup count
                    fresh = [e for e in pushback
                             if id(e[2]) not in counted_pushback]
                    counted_pushback.update(id(e[2]) for e in fresh)
                    if fresh:
                        self._m_pushbacks.inc(len(fresh))
                if len(batch) >= max_batch_size:
                    break
                if max_rows and rows >= max_rows:
                    break
                remaining = fill_deadline - self._now()
                if remaining <= 0:
                    break
                if self._stopped or (stop is not None and stop.is_set()):
                    break
                # woken early by put(): loop re-scans the heap
                self._cond.wait(timeout=remaining)
            return batch, expired

    def _fill_grouped(self, max_batch_size: int, max_rows: Optional[int],
                      batch_timeout_s: float,
                      stop: Optional[threading.Event], grouping):
        """Tenant-aware batch formation (cross-tenant continuous batching).
        Caller holds ``self._cond`` and guarantees a non-empty heap.

        ``grouping`` supplies three hooks:

        * ``key(item)`` — hashable tenant / shared-program identity; items
          with equal keys dispatch as ONE device group.
        * ``bucket(key, rows)`` — the compile-bucket ``rows`` pads to for
          that group's engine.  Formation fills one group's sub-batch to a
          bucket boundary before opening the next, so a cycle of N tiny
          tenant groups no longer pads N buckets.
        * ``limit(key)`` — optional per-cycle item cap (the tenant's
          in-flight quota bound): a capped tenant YIELDS its slots to
          other groups instead of fragmenting the cycle.

        Fairness is deficit round robin over rows: every group with queued
        work earns a per-cycle quantum; groups are served in deficit order
        (ties resolve to the EDF-earliest item) and a group's take spends
        its credit, so a flooding tenant that filled this batch sorts
        behind the tenants it displaced on the next one.  Items not taken
        are pushed back with their ORIGINAL heap keys — the same
        starvation-free deferral contract as row-budget pushback.
        """

        batch: List[object] = []
        expired: List[object] = []
        counted_pushback: set = set()
        rows = 0
        group_rows: Dict[object, int] = {}
        group_items: Dict[object, int] = {}
        fill_deadline = self._now() + (batch_timeout_s
                                       if max_batch_size > 1 else 0.0)
        while True:
            now = self._now()
            # bounded EDF-prefix scan: pop live candidates (expiry and
            # done handling identical to the plain path); anything beyond
            # the scan window stays heap-resident untouched
            scan_limit = max(16, 4 * max_batch_size)
            candidates: List[Tuple[float, int, object]] = []
            while self._heap and len(candidates) < scan_limit:
                eff, seq, item = heapq.heappop(self._heap)
                if getattr(item, "done", False):
                    self._account_pop(item)
                    continue
                if self._is_expired(item, now):
                    self._account_pop(item)
                    expired.append(item)
                    if self._m_expired is not None:
                        self._m_expired.inc(**{
                            "class": getattr(item, "klass", "batch")})
                    continue
                candidates.append((eff, seq, item))
            groups: Dict[object, List[Tuple[float, int, object]]] = {}
            for entry in candidates:
                try:
                    key = grouping.key(entry[2])
                except Exception:
                    key = None
                groups.setdefault(key, []).append(entry)
            # DRR credit: every group with queued work earns a row
            # quantum (capped so an idle-then-bursty group cannot hoard
            # unbounded credit); state is LRU-bounded across tenant churn
            quantum = float(max(1, max_rows or max_batch_size)) \
                / max(1, len(groups))
            for key in groups:
                self._drr[key] = max(
                    min(self._drr.get(key, 0.0) + quantum, 4.0 * quantum),
                    -4.0 * quantum)
                self._drr.move_to_end(key)
            while len(self._drr) > 256:
                self._drr.popitem(last=False)
            serve_order = sorted(
                groups, key=lambda k: (-self._drr.get(k, 0.0),
                                       groups[k][0][:2]))
            pushback: List[Tuple[float, int, object]] = []
            for gi, key in enumerate(serve_order):
                entries = groups[key]
                try:
                    cap = grouping.limit(key)
                except Exception:
                    cap = None
                # EDF-ordered prefix of this group that fits the global
                # capacity, the row budget and the tenant's per-cycle cap
                fit_n, total = 0, rows
                for eff, seq, item in entries:
                    if len(batch) + fit_n >= max_batch_size:
                        break
                    if max_rows and total >= max_rows:
                        break
                    if cap is not None and \
                            group_items.get(key, 0) + fit_n >= cap:
                        break
                    if (batch or fit_n) and max_rows \
                            and total + item.rows > max_rows:
                        break
                    fit_n += 1
                    total += item.rows
                # bucket-boundary trim: while OTHER groups still have
                # work, cut this group at the largest prefix landing
                # exactly on its compile bucket — padding one tenant's
                # sub-batch while another tenant's real rows wait is the
                # waste this packer exists to remove.  No boundary
                # reachable (or last group standing): take the full fit.
                more_elsewhere = bool(pushback) or any(
                    groups[k2] for k2 in serve_order[gi + 1:])
                if fit_n and more_elsewhere:
                    base = group_rows.get(key, 0)
                    cum, best = base, None
                    for i in range(fit_n):
                        cum += entries[i][2].rows
                        try:
                            boundary = grouping.bucket(key, cum) == cum
                        except Exception:
                            boundary = True
                        if boundary:
                            best = i + 1
                    if best is not None:
                        fit_n = best
                for eff, seq, item in entries[:fit_n]:
                    self._account_pop(item)
                    if self._m_queue_wait is not None:
                        self._m_queue_wait.observe(
                            max(0.0, now - item.t_enqueued),
                            **{"class": getattr(item, "klass", "batch")})
                    batch.append(item)
                    rows += item.rows
                    group_rows[key] = group_rows.get(key, 0) + item.rows
                    group_items[key] = group_items.get(key, 0) + 1
                    self._drr[key] = self._drr.get(key, 0.0) - item.rows
                pushback.extend(entries[fit_n:])
            if not batch and pushback:
                # progress guarantee: every group capped out (limit()=0
                # misconfiguration, boundary trims) must never spin the
                # dispatcher on an empty batch — take the EDF-earliest
                # candidate unconditionally, with the SAME per-item
                # accounting as a normal take (queue-wait observation,
                # DRR debit) so the guarantee path cannot skew either
                entry = min(pushback, key=lambda e: e[:2])
                pushback.remove(entry)
                item = entry[2]
                self._account_pop(item)
                if self._m_queue_wait is not None:
                    self._m_queue_wait.observe(
                        max(0.0, now - item.t_enqueued),
                        **{"class": getattr(item, "klass", "batch")})
                batch.append(item)
                rows += item.rows
                try:
                    key = grouping.key(item)
                except Exception:
                    key = None
                group_rows[key] = group_rows.get(key, 0) + item.rows
                group_items[key] = group_items.get(key, 0) + 1
                self._drr[key] = self._drr.get(key, 0.0) - item.rows
            for entry in pushback:
                heapq.heappush(self._heap, entry)
            if pushback and self._m_pushbacks is not None:
                fresh = [e for e in pushback
                         if id(e[2]) not in counted_pushback]
                counted_pushback.update(id(e[2]) for e in fresh)
                if fresh:
                    self._m_pushbacks.inc(len(fresh))
            if len(batch) >= max_batch_size:
                break
            if max_rows and rows >= max_rows:
                break
            remaining = fill_deadline - self._now()
            if remaining <= 0:
                break
            if self._stopped or (stop is not None and stop.is_set()):
                break
            # woken early by put(): loop re-scans the heap
            self._cond.wait(timeout=remaining)
        return batch, expired

    def drain(self) -> List[object]:
        """Remove and return every queued (not-done) item — the server's
        wedge/shutdown path fails them so no handler thread leaks."""

        with self._cond:
            items = [item for _, _, item in self._heap
                     if not getattr(item, "done", False)]
            self._heap.clear()
            self._depths = {k: 0 for k in PRIORITY_CLASSES}
            self._queued_rows = 0
            return items

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class StagingBuffer:
    """Bounded handoff for the double-buffered host→device staging pipeline
    (ISSUE 6; Podracer's pipelined host/device split, PAPERS.md arXiv
    2104.06272).

    The server's batcher thread forms scheduler batches and starts their
    host→device upload (``engine.stage_rows`` — ``jax.device_put`` is
    asynchronous), then :meth:`put`\\ s them here; the dispatcher thread
    :meth:`get`\\ s batches whose rows are already device-resident.  With
    ``depth=1`` the steady state is the classic double buffer: one batch
    computing on the device, one staged and ready, one being formed — the
    device never waits on an H2D copy between scheduler batches.

    :meth:`get` also returns how long the staged batch sat ready before
    dispatch — the measured upload/compute overlap the server surfaces as
    ``dks_staging_overlap_seconds_total`` (0 means the dispatcher was
    already waiting, i.e. the host is the bottleneck; sustained positive
    values mean the upload fully hid behind device work).
    """

    def __init__(self, depth: int = 1, mem_account=None,
                 nbytes_fn=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        # optional memory-ledger account: each staged slot pins device
        # buffers between put and get, so slots charge computed nbytes
        # under owner=staging for their (bounded, but real) lifetime
        self._mem = mem_account
        self._mem_nbytes = nbytes_fn

    def _mem_charge(self, item) -> None:
        if self._mem is not None and self._mem_nbytes is not None:
            try:
                self._mem.charge(id(item), int(self._mem_nbytes(item)))
            except Exception:
                logger.exception("staging ledger charge failed")

    def _mem_release(self, item) -> None:
        if self._mem is not None:
            self._mem.release(id(item))

    def put(self, item, stop: Optional[threading.Event] = None,
            poll_s: float = 0.1) -> bool:
        """Block until a staging slot frees (bounded: at most ``depth``
        staged batches hold device buffers at once).  Returns ``False``
        without enqueueing once ``stop`` is set — the caller owns failing
        the batch."""

        entry = (item, time.monotonic())
        self._mem_charge(item)
        while True:
            if stop is not None and stop.is_set():
                self._mem_release(item)
                return False
            try:
                self._q.put(entry, timeout=poll_s)
                return True
            except queue.Full:
                continue

    def get(self, stop: Optional[threading.Event] = None,
            poll_s: float = 0.1):
        """``(item, ready_s)`` for the next staged batch — ``ready_s`` is
        the seconds it sat device-ready before this pop.  ``None`` once
        ``stop`` is set and the buffer is empty (staged leftovers are still
        delivered first so no request silently leaks)."""

        while True:
            try:
                item, t_ready = self._q.get(timeout=poll_s)
            except queue.Empty:
                if stop is not None and stop.is_set():
                    return None
                continue
            self._mem_release(item)
            return item, max(0.0, time.monotonic() - t_ready)

    def drain(self) -> List:
        """Remove and return every still-staged item (shutdown path)."""

        items = []
        while True:
            try:
                item = self._q.get_nowait()[0]
            except queue.Empty:
                return items
            self._mem_release(item)
            items.append(item)


class FIFOScheduler(SLOScheduler):
    """Arrival-order baseline with no deadline semantics.

    Same interface (so the server and the benchmark can swap policies with
    one knob) but orders purely by arrival sequence and never expires
    anything — this is the exact behaviour of the round-4 FIFO queue, kept
    as the control arm for ``benchmarks/scheduling_bench.py``.
    """

    def _effective_deadline(self, item) -> float:
        return 0.0  # heap tie-breaks on seq == arrival order

    def _is_expired(self, item, now: float) -> bool:
        return False


def make_scheduler(policy: str = "slo",
                   class_budgets: Optional[Dict[str, float]] = None,
                   now=time.monotonic) -> SLOScheduler:
    if policy == "slo":
        return SLOScheduler(class_budgets=class_budgets, now=now)
    if policy == "fifo":
        return FIFOScheduler(class_budgets=class_budgets, now=now)
    raise ValueError(f"unknown scheduling policy {policy!r} "
                     f"(expected 'slo' or 'fifo')")
