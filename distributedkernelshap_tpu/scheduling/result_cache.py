"""Content-addressed explanation cache.

KernelSHAP is deterministic here by construction: the coalition plan is a
pure function of ``(M, nsamples, seed)`` and the solve runs in pinned-f32
on a fixed background, so two requests carrying the same instance rows
against the same fitted explainer produce byte-identical Explanation JSON.
Recomputing one is pure waste — at production traffic the same handful of
rows (dashboard entities, demo inputs, retried requests) dominates, and
every duplicate served from host memory is a device batch slot freed for a
novel request.

Keys are content-addressed: SHA-256 over the request's instance rows
(dtype + shape + bytes) combined with a *model fingerprint* — background
data digest, link, grouping, seed and the deployment's pinned
``explain_kwargs``.  Changing any of these (a refit on new background, a
different link, new grouping) changes the fingerprint, so stale entries
are unreachable rather than invalidated: eviction is purely LRU under a
byte budget.

The cache stores the exact JSON payload string the server would have sent,
so a hit is bit-identical to the original response — additivity and all.
"""

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np


def array_fingerprint(array: np.ndarray) -> str:
    """SHA-256 digest of an array's dtype, shape and contents."""

    a = np.ascontiguousarray(array)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def _update_structured(h, value) -> None:
    """Feed ``value`` into the hash with full content: ``repr`` alone is
    unsafe for ndarrays (numpy elides the middle of large arrays with
    ``...``, so two groupings differing only in the elided region would
    collide) — arrays hash via :func:`array_fingerprint`, containers
    recurse, and everything else falls back to ``repr``."""

    if isinstance(value, np.ndarray):
        h.update(b"nd:")
        h.update(array_fingerprint(value).encode())
    elif isinstance(value, (list, tuple)):
        h.update(f"seq{len(value)}:".encode())
        for item in value:
            _update_structured(h, item)
    elif isinstance(value, dict):
        h.update(f"map{len(value)}:".encode())
        for key in sorted(value, key=repr):
            h.update(repr(key).encode())
            _update_structured(h, value[key])
    else:
        h.update(repr(value).encode())


def model_fingerprint(model, explain_kwargs: Optional[dict] = None) -> str:
    """Fingerprint of everything besides the instance rows that determines
    an explanation: background digest, link, grouping, seed, pinned explain
    options and the predictor's in-process identity.

    A model may pin its own ``fingerprint`` attribute (e.g. a hash of
    checkpoint weights, so restarts share keys); otherwise the fingerprint
    is derived by introspection.  Predictor identity falls back to
    ``id(predictor)``, which is correct within one process — a *different*
    predictor object can only cause misses, never wrong answers.
    """

    explicit = getattr(model, "fingerprint", None)
    if isinstance(explicit, str) and explicit:
        return explicit
    h = hashlib.sha256()
    explainer = getattr(model, "explainer", model)
    engine = getattr(explainer, "_explainer", None)
    background = getattr(engine, "background", None)
    if background is not None:
        h.update(array_fingerprint(np.asarray(background)).encode())
    bg_weights = getattr(engine, "bg_weights", None)
    if bg_weights is not None:
        h.update(array_fingerprint(np.asarray(bg_weights)).encode())
    h.update(repr(getattr(explainer, "link", None)).encode())
    h.update(repr(getattr(explainer, "seed", None)).encode())
    _update_structured(h, getattr(engine, "groups", None))
    kwargs = (explain_kwargs if explain_kwargs is not None
              else getattr(model, "explain_kwargs", None))
    _update_structured(h, kwargs or {})
    predictor = getattr(engine, "predictor",
                        getattr(explainer, "predictor", None))
    h.update(f"{type(predictor).__qualname__}:{id(predictor)}".encode())
    return h.hexdigest()


def request_cache_key(array: np.ndarray, model_fp: str) -> str:
    """Key for one request: instance-rows digest x model fingerprint."""

    return f"{model_fp}:{array_fingerprint(array)}"


class ResultCache:
    """Thread-safe LRU cache of response payload strings, bounded by an
    approximate byte budget (UTF-8 length of the stored payloads; the JSON
    here is ASCII so ``len(payload)`` is the byte count)."""

    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive "
                             "(use no cache instead of a zero-byte one)")
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[str, str]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            payload = self._entries.get(key)
            if payload is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return payload

    def put(self, key: str, payload: str) -> None:
        size = len(payload)
        if size > self.max_bytes:
            return  # larger than the whole budget: caching it evicts all
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = payload
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
                self._evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def current_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions, "entries":
                    len(self._entries), "bytes": self._bytes}
