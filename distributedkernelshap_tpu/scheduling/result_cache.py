"""Content-addressed explanation cache.

KernelSHAP is deterministic here by construction: the coalition plan is a
pure function of ``(M, nsamples, seed)`` and the solve runs in pinned-f32
on a fixed background, so two requests carrying the same instance rows
against the same fitted explainer produce byte-identical Explanation JSON.
Recomputing one is pure waste — at production traffic the same handful of
rows (dashboard entities, demo inputs, retried requests) dominates, and
every duplicate served from host memory is a device batch slot freed for a
novel request.

Keys are content-addressed: SHA-256 over the request's instance rows
(dtype + shape + bytes) combined with a *model fingerprint* — background
data digest, link, grouping, seed and the deployment's pinned
``explain_kwargs``.  Changing any of these (a refit on new background, a
different link, new grouping) changes the fingerprint, so stale entries
are unreachable rather than invalidated: eviction is purely LRU under a
byte budget.

The cache stores the exact JSON payload string the server would have sent,
so a hit is bit-identical to the original response — additivity and all.
"""

import hashlib
import logging
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# weak-fingerprint accounting (process-global, rendered via a registry
# callback like the explain-path counters): every model_fingerprint that
# had to fall back to in-process identity for its predictor — the
# stale-cache-across-restart hazard flagged since PR 2 — is counted here
# and warned about loudly ONCE per process instead of silently.
_weak_lock = threading.Lock()
_weak_count = 0
_weak_warned = False


def record_weak_fingerprint(predictor) -> None:
    global _weak_count, _weak_warned
    with _weak_lock:
        _weak_count += 1
        first = not _weak_warned
        _weak_warned = True
    if first:
        logger.warning(
            "model fingerprint fell back to in-process identity for %s: "
            "cache keys will NOT survive a restart and an in-place "
            "predictor swap is undetectable.  Register the model through "
            "the ModelRegistry (content fingerprints) or pin "
            "model.fingerprint explicitly.  Counted in "
            "dks_result_cache_weak_fingerprint_total.",
            type(predictor).__name__)


def weak_fingerprint_total() -> float:
    with _weak_lock:
        return float(_weak_count)


def attach_weak_fingerprint_metric(registry) -> None:
    """Register ``dks_result_cache_weak_fingerprint_total`` on
    ``registry``: model fingerprints that fell back to in-process
    predictor identity (restart-unstable cache keys)."""

    registry.counter(
        "dks_result_cache_weak_fingerprint_total",
        "Model fingerprints derived from in-process predictor identity "
        "(id()) because the predictor exposed no hashable content — such "
        "cache keys do not survive a restart.  Registry-registered "
        "models always get content fingerprints and never count here.",
    ).set_function(weak_fingerprint_total)


def array_fingerprint(array: np.ndarray) -> str:
    """SHA-256 digest of an array's dtype, shape and contents."""

    a = np.ascontiguousarray(array)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def _update_structured(h, value) -> None:
    """Feed ``value`` into the hash with full content: ``repr`` alone is
    unsafe for ndarrays (numpy elides the middle of large arrays with
    ``...``, so two groupings differing only in the elided region would
    collide) — arrays hash via :func:`array_fingerprint`, containers
    recurse, and everything else falls back to ``repr``."""

    if isinstance(value, np.ndarray):
        h.update(b"nd:")
        h.update(array_fingerprint(value).encode())
    elif isinstance(value, (list, tuple)):
        h.update(f"seq{len(value)}:".encode())
        for item in value:
            _update_structured(h, item)
    elif isinstance(value, dict):
        h.update(f"map{len(value)}:".encode())
        for key in sorted(value, key=repr):
            h.update(repr(key).encode())
            _update_structured(h, value[key])
    else:
        h.update(repr(value).encode())


def _is_array_like(value) -> bool:
    """Numpy/JAX arrays (anything exposing shape+dtype that numpy can
    materialise) — the content a predictor's fingerprint hashes."""

    return hasattr(value, "shape") and hasattr(value, "dtype") \
        and not np.isscalar(value)


def _collect_content(value, h, depth: int = 0) -> int:
    """Feed every array reachable from ``value`` (attr dicts, sequences,
    nested predictors — bounded depth) into ``h``; returns how many
    arrays were hashed."""

    if depth > 4:
        return 0
    if value is None or isinstance(value, (str, bytes, bool, int, float)):
        # scalar config (activation names, out_transform, offsets, ...)
        # is part of the content — two predictors sharing arrays but
        # differing in a plain attribute must NOT collide — but scalars
        # alone do not make a fingerprint "content-based" (return 0):
        # without parameter arrays the id() fallback still applies
        h.update(repr(value).encode())
        return 0
    if _is_array_like(value):
        try:
            h.update(array_fingerprint(np.asarray(value)).encode())
            return 1
        except Exception:
            return 0
    if isinstance(value, (list, tuple)):
        return sum(_collect_content(v, h, depth + 1) for v in value)
    if isinstance(value, dict):
        found = 0
        for k in sorted(value, key=repr):
            h.update(repr(k).encode())
            found += _collect_content(value[k], h, depth + 1)
        return found
    attrs = getattr(value, "__dict__", None)
    if attrs is not None and depth < 4 and hasattr(value, "n_outputs"):
        # nested predictors (composite lifts hold member predictors)
        found = 0
        for key in sorted(attrs):
            h.update(repr(key).encode())
            found += _collect_content(attrs[key], h, depth + 1)
        return found
    return 0


def predictor_fingerprint(predictor) -> Tuple[str, bool]:
    """``(digest, weak)`` for a predictor: a content hash over its class
    name and every parameter array reachable from its attributes
    (coefficients, tree tensors, TT cores, MLP layers — stable across
    restarts and across distinct-but-identical objects), or — when no
    array content is reachable (host callbacks, stub models) — the
    historical in-process identity with ``weak=True``."""

    h = hashlib.sha256()
    h.update(type(predictor).__qualname__.encode())
    # predictors that publish their own content bytes (TT cores, lifted
    # neural graphs, param-carrying JaxPredictors) are authoritative:
    # the declared bytes ARE the deployment identity (None means the
    # predictor has no content — fall through to introspection)
    fp_bytes = getattr(predictor, "fingerprint_bytes", None)
    if callable(fp_bytes):
        try:
            declared = fp_bytes()
        except Exception:
            declared = None
        if declared is not None:
            h.update(declared)
            return h.hexdigest(), False
    found = _collect_content(getattr(predictor, "__dict__", None) or {}, h)
    if found:
        return h.hexdigest(), False
    return (f"{type(predictor).__qualname__}:{id(predictor)}", True)


def model_fingerprint(model, explain_kwargs: Optional[dict] = None,
                      count_weak: bool = True) -> str:
    """Fingerprint of everything besides the instance rows that determines
    an explanation: background digest, link, grouping, seed, pinned explain
    options and the predictor's in-process identity.

    A model may pin its own ``fingerprint`` attribute (the registry does —
    ``model_id@vN:<content digest>`` — so restarts share keys); otherwise
    the fingerprint is derived by introspection.  Predictor identity is a
    CONTENT hash of its parameter arrays when any are reachable
    (:func:`predictor_fingerprint`); only parameterless predictors (host
    callbacks, stubs) fall back to ``id(predictor)`` — correct within one
    process (a different object can only cause misses, never wrong
    answers) but restart-unstable, so the fallback is counted in
    ``dks_result_cache_weak_fingerprint_total`` and warned about once.
    """

    explicit = getattr(model, "fingerprint", None)
    if isinstance(explicit, str) and explicit:
        return explicit
    h = hashlib.sha256()
    explainer = getattr(model, "explainer", model)
    engine = getattr(explainer, "_explainer", None)
    background = getattr(engine, "background", None)
    if background is not None:
        h.update(array_fingerprint(np.asarray(background)).encode())
    bg_weights = getattr(engine, "bg_weights", None)
    if bg_weights is not None:
        h.update(array_fingerprint(np.asarray(bg_weights)).encode())
    h.update(repr(getattr(explainer, "link", None)).encode())
    h.update(repr(getattr(explainer, "seed", None)).encode())
    _update_structured(h, getattr(engine, "groups", None))
    kwargs = (explain_kwargs if explain_kwargs is not None
              else getattr(model, "explain_kwargs", None))
    _update_structured(h, kwargs or {})
    predictor = getattr(engine, "predictor",
                        getattr(explainer, "predictor", None))
    digest, weak = predictor_fingerprint(predictor)
    if weak and count_weak:
        # count_weak=False is the registry's ingest path: it namespaces
        # the digest under a declared (model_id, version), so even a
        # parameterless predictor's keys are restart-stable
        record_weak_fingerprint(predictor)
    h.update(digest.encode())
    return h.hexdigest()


def request_cache_key(array: np.ndarray, model_fp: str) -> str:
    """Key for one request: instance-rows digest x model fingerprint."""

    return f"{model_fp}:{array_fingerprint(array)}"


class ResultCache:
    """Thread-safe LRU cache of response payload strings, bounded by an
    approximate byte budget (UTF-8 length of the stored payloads; the JSON
    here is ASCII so ``len(payload)`` is the byte count).

    Entries carry a *fidelity*: the reported error bound of the stored
    payload (``est_err``, 0.0 = full fidelity — every pre-anytime payload).
    One content key stores the HIGHEST-fidelity payload seen (a coarser
    anytime answer never overwrites a finer one), and a lookup only hits
    when the stored fidelity satisfies the caller's error budget —
    budget-less callers (``max_err=None``) are served full-fidelity
    entries only, which is exactly the historical behaviour."""

    def __init__(self, max_bytes: int, mem_account=None):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive "
                             "(use no cache instead of a zero-byte one)")
        self.max_bytes = int(max_bytes)
        # key -> (payload, est_err)
        self._entries: "OrderedDict[str, Tuple[str, float]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._audit_rejects = 0
        # optional memory-ledger account: payload bytes are host memory,
        # but they hold device work hostage (a hit IS a device batch slot
        # freed), so the ledger tracks them under owner=result_cache next
        # to the true device buffers.  Charges are namespaced by this
        # cache instance — several servers may share one process account.
        self._mem = mem_account
        self._mem_token = object()

    def _mem_charge(self, key: str, size: int) -> None:
        if self._mem is not None:
            self._mem.charge((self._mem_token, key), size, sweep=False)

    def _mem_release(self, key: str) -> None:
        if self._mem is not None:
            self._mem.release((self._mem_token, key))

    def get(self, key: str,
            max_err: Optional[float] = None) -> Optional[str]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._misses += 1
                return None
            payload, est_err = entry
            if est_err > (0.0 if max_err is None else max_err):
                # stored answer is coarser than this caller tolerates:
                # a fidelity miss costs device work like a cold miss
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return payload

    def put(self, key: str, payload: str, est_err: float = 0.0,
            screened: bool = False) -> None:
        """Insert (keep-best).  ``screened=True`` means the quality
        invariant screen is the caller's responsibility (the server
        queues every finalized answer for its deferred audit and
        ``invalidate``\\ s any entry whose payload fails it); unscreened
        callers pay the screen here — a phi payload violating
        additivity/finiteness must never become a bit-identical repeat
        offender (audit-on-insert, ``observability/quality.py``)."""

        size = len(payload)
        if size > self.max_bytes:
            return  # larger than the whole budget: caching it evicts all
        est_err = max(0.0, float(est_err))
        if not screened:
            from distributedkernelshap_tpu.observability.quality import (
                cacheable_payload,
            )

            if not cacheable_payload(payload, final_err=est_err):
                with self._lock:
                    self._audit_rejects += 1
                return
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                if old[1] < est_err:
                    # keep-best: the stored payload is strictly finer;
                    # equal fidelity replaces (historical last-write-wins)
                    self._entries.move_to_end(key)
                    return
                self._entries.pop(key)
                self._bytes -= len(old[0])
                self._mem_release(key)
            self._entries[key] = (payload, est_err)
            self._bytes += size
            self._mem_charge(key, size)
            while self._bytes > self.max_bytes and self._entries:
                ev_key, (evicted, _err) = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
                self._evictions += 1
                self._mem_release(ev_key)
        if self._mem is not None:
            # the ledger's pressure sweep re-enters this cache through
            # evict_bytes, so it must run with our lock released
            self._mem.ledger.poke()

    def invalidate(self, key: str, audit: bool = False) -> bool:
        """Remove one entry outright.  ``audit=True`` is the deferred
        quality audit's poison-removal hook: the server inserts at
        finalize time (keeping the hot path lock-free of the screen) and
        the audit thread pulls the entry back out if the payload fails
        the invariant screen — counted with the insert-time rejects in
        ``audit_rejects``."""

        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self._bytes -= len(entry[0])
            self._mem_release(key)
            if audit:
                self._audit_rejects += 1
        return True

    def evict_bytes(self, nbytes: int) -> int:
        """LRU-evict until at least ``nbytes`` are freed (or the cache
        is empty); the memory ledger's pressure hook.  Evicted answers
        recompute bit-identically on the next request — content-
        addressed keys make eviction always safe."""

        freed = 0
        with self._lock:
            while self._entries and freed < int(nbytes):
                key, (payload, _err) = self._entries.popitem(last=False)
                self._bytes -= len(payload)
                self._evictions += 1
                freed += len(payload)
                self._mem_release(key)
        return freed

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def current_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions,
                    "audit_rejects": self._audit_rejects,
                    "entries": len(self._entries), "bytes": self._bytes}
