"""Elastic SLO-driven fleet autoscaling: burn-rate + queue-signal scaler
with predictive pre-warm and drain-based scale-down.

PRs 5-7 made one replica fast; the fleet itself was still a fixed-size
``ReplicaManager`` — under diurnal traffic a static fleet either
overprovisions replica-seconds all day or blows the interactive p99 SLO
at peak.  This module closes ROADMAP open item 1: a control loop that
sizes the replica fleet from the live signals the stack already
computes, the way Podracer scales actors and learners independently
(PAPERS.md, arXiv 2104.06272):

* **SLO burn rate** — the fan-in proxy's :class:`~distributedkernelshap_
  tpu.observability.statusz.HealthEngine` evaluates multi-window
  burn-rate conditions every tick (``observability/slo.py``); any
  breached SLO is the strongest scale-up signal (the budget is actively
  burning — capacity is late, not early).
* **Queue pressure** — each ready replica's ``/statusz?format=json``
  reports its per-class queue depths and its admission estimator's
  EDF-aware projected wait (``scheduling/admission.py`` /
  ``SLOScheduler.rows_ahead``); the scaler aggregates a fleet-level
  projected wait from total queued rows over a fleet-capacity EWMA
  (:class:`~distributedkernelshap_tpu.scheduling.admission.
  ServiceRateEstimator` with :meth:`capacity_hint` rescaling it the
  moment fleet size changes, so the projection neither lags a scale-up
  nor a drain).
* **Rate trend (predictive pre-warm)** — the proxy health engine's
  time-series store answers ``rate(dks_fanin_forwarded_total)`` over a
  short and a long window; traffic ramping (short ≫ long) triggers a
  scale-up BEFORE queues build, so the new replica's warmup ladder
  (PR 5, ``DKS_WARMUP``) finishes as the load arrives instead of after.

**Scale-up is routable in seconds**: a spawned worker pre-warms through
the existing warmup ladder in the ``warming`` state (non-routable — the
proxy's prober admits it the moment ``/healthz`` flips 200; and
non-restartable — the supervisor keys restarts on process exit, and a
warming process is alive).  A configurable **warm-standby pool** keeps
fully-warmed spares out of rotation; activating one
(:meth:`~distributedkernelshap_tpu.serving.replicas.FanInProxy.
activate_standby`) is instant, and the pool is replenished in the
background.

**Scale-down drains**: the victim is marked unroutable at the proxy
(``start_drain`` — in-flight and queued work keeps answering through
the replica's own scheduler), the scaler polls its ``/statusz`` until
queues and in-flight batches are empty for consecutive polls, then
retires it through the supervisor (``ReplicaSupervisor.retire`` — the
exit is on purpose, never restarted).  Stragglers hitting the final
``server.stop()`` get the wedge/claim path's retriable pre-dispatch 503
and fail over — zero lost, zero duplicated answers (asserted by
``benchmarks/autoscale_bench.py --check``).

The scaler never flaps: scale-up needs ``up_ticks`` consecutive signal
ticks and respects ``up_cooldown_s``; scale-down needs ``down_ticks``
and ``down_cooldown_s`` and is held while anything is warming or
draining; both respect ``min_replicas``/``max_replicas``.  A wedged or
killed scaler (chaos site ``scaler.tick``) degrades to the CURRENT
fleet size — the loop only ever acts, never holds the fleet hostage.

``autoscale=off`` is the default: a ``ReplicaManager`` without an
:class:`AutoscalerConfig` serves its fixed ``n_replicas`` exactly as
before.
"""

import concurrent.futures
import http.client
import json
import logging
import threading
import time
from typing import Dict, List, Optional

from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.scheduling.admission import (
    ServiceRateEstimator,
)

logger = logging.getLogger(__name__)


class _ScalerCrashed(Exception):
    """Injected thread-scoped crash (chaos site ``scaler.tick``)."""


class AutoscalerConfig:
    """Knobs for one :class:`Autoscaler` (defaults are production-shaped;
    the benchmark tightens the timing knobs to fit a replay).

    Parameters
    ----------
    min_replicas, max_replicas
        Hard bounds on serving-intent replicas (ready + warming;
        standbys are extra).  The scaler never drains below ``min`` and
        never spawns above ``max`` — a crashed replica awaiting its
        supervisor respawn ("down") counts against ``max`` too, so the
        scaler can't spawn a replacement the restart then overshoots.
    warm_standby
        Fully-warmed spares held out of rotation.  Scale-up activates a
        standby instantly (no spawn+warm on the critical path) and
        replenishes the pool in the background.
    interval_s
        Control-loop tick period.
    up_ticks, down_ticks
        Hysteresis: consecutive signal ticks required before acting.
        Down is deliberately much slower than up — adding late burns the
        SLO, removing late only burns replica-seconds.
    up_cooldown_s, down_cooldown_s
        Minimum spacing between same-direction scale actions.
    queue_wait_up_s
        Fleet projected wait (total queued rows / fleet-capacity EWMA)
        above which capacity is late; should sit comfortably under the
        interactive latency SLO threshold.
    replica_wait_up_s
        Per-replica EDF-aware projected interactive wait (replica
        ``/statusz`` ``projected_wait_s``) above which that replica is
        drowning even if the fleet average looks fine.
    trend_factor, trend_window_short_s, trend_window_long_s
        Predictive pre-warm: scale up when the short-window forwarded
        REQUEST rate exceeds ``trend_factor`` x the long-window rate
        (the ratio is unitless, so request counts are fine there) AND
        the served-rows demand is at least ``trend_min_utilization`` of
        fleet rows/s capacity (a ramp from nothing to nearly-nothing
        must not spawn).  Utilization is rows over rows — demand comes
        from differentiating the replicas' ``rows_served_total``, never
        from the request rate, because requests carry arbitrary row
        counts.
    down_utilization
        Scale down when observed rows/s demand could be served by one
        FEWER replica at or below this utilization (and no queue
        pressure, no
        breach) for ``down_ticks`` ticks.
    drain_timeout_s
        Upper bound on a drain; past it the victim is retired anyway
        (its own ``server.stop()`` answers stragglers with retriable
        503s — the proxy fails them over).
    drain_settle_polls
        Consecutive empty (no queued, no in-flight) ``/statusz`` polls
        required before a draining victim is retired — absorbs the
        pick-to-enqueue race on requests routed just before the drain
        flag flipped.
    statusz_timeout_s
        Per-replica ``/statusz`` poll budget; an unreachable replica
        simply contributes no signal that tick.
    """

    def __init__(self,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 warm_standby: int = 0,
                 interval_s: float = 1.0,
                 up_ticks: int = 2,
                 down_ticks: int = 10,
                 up_cooldown_s: float = 5.0,
                 down_cooldown_s: float = 30.0,
                 queue_wait_up_s: float = 0.35,
                 replica_wait_up_s: float = 0.35,
                 trend_factor: float = 1.5,
                 trend_window_short_s: float = 5.0,
                 trend_window_long_s: float = 30.0,
                 trend_min_utilization: float = 0.5,
                 down_utilization: float = 0.6,
                 drain_timeout_s: float = 60.0,
                 drain_settle_polls: int = 2,
                 statusz_timeout_s: float = 2.0):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if warm_standby < 0:
            raise ValueError("warm_standby must be >= 0")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.warm_standby = int(warm_standby)
        self.interval_s = float(interval_s)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.queue_wait_up_s = float(queue_wait_up_s)
        self.replica_wait_up_s = float(replica_wait_up_s)
        self.trend_factor = float(trend_factor)
        self.trend_window_short_s = float(trend_window_short_s)
        self.trend_window_long_s = float(trend_window_long_s)
        self.trend_min_utilization = float(trend_min_utilization)
        self.down_utilization = float(down_utilization)
        self.drain_timeout_s = float(drain_timeout_s)
        self.drain_settle_polls = max(1, int(drain_settle_polls))
        self.statusz_timeout_s = float(statusz_timeout_s)

    def to_dict(self) -> Dict:
        return dict(vars(self))


class Autoscaler:
    """The control loop (see module doc).

    Parameters
    ----------
    fleet
        Anything exposing the elastic hooks ``spawn_replica(standby=...)
        -> Optional[int]`` and ``retire_replica(index)`` —
        :class:`~distributedkernelshap_tpu.serving.replicas.
        ReplicaManager` for a subprocess fleet, or the benchmark's
        in-process fleet.  May be ``None`` for metrics-only registration
        (``scripts/obs_check.py``).
    proxy
        The :class:`~distributedkernelshap_tpu.serving.replicas.
        FanInProxy` whose rotation is being sized.  Supplies replica
        states, the health engine (SLO statuses + time-series store) and
        the metrics registry the ``dks_autoscale_*`` series register on.
    config
        :class:`AutoscalerConfig`; ``None`` uses defaults.
    fault_injector
        Chaos hook, consulted at site ``scaler.tick`` with THREAD-scoped
        crash semantics (``resilience/faults.py``) — a crashed or wedged
        scaler kills only this loop; the fleet keeps serving at its
        current size.
    """

    def __init__(self, fleet, proxy, config: Optional[AutoscalerConfig] = None,
                 fault_injector=None):
        self.fleet = fleet
        self.proxy = proxy
        self.config = config or AutoscalerConfig()
        # pod-as-replica: with a pod fleet (ReplicaManager
        # pod_processes > 1) every scale event spawns/retires a WHOLE pod
        # and every provisioned second costs P process-seconds — the
        # replica-seconds meter scales by this so chargeback matches what
        # the cluster actually runs
        self.unit_processes = max(1, int(getattr(fleet, "pod_processes",
                                                 1) or 1))
        self._faults = fault_injector
        self._flight = flightrec()
        # fleet-capacity EWMA in rows/s, capacity-hinted on every scale
        # event so projections track the NEW size immediately
        self.estimator = ServiceRateEstimator(alpha=0.3)
        self._hinted_ready: Optional[int] = None
        # hysteresis counters + cooldown stamps
        self._up_streak = 0
        self._down_streak = 0
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        # draining victims: index -> bookkeeping (mutated by the scaler
        # thread under self._lock — statusz_panel iterates it from proxy
        # handler threads)
        self._draining: Dict[int, Dict] = {}
        # served-rows demand: previous per-replica rows_served_total
        # snapshot, differentiated each tick into rows/s
        self._rows_prev: Optional[Dict[int, float]] = None
        self._rows_prev_t: float = 0.0
        # replica-seconds accrue over real elapsed time (a tick blocked
        # on statusz timeouts must still integrate correctly)
        self._accrual_t: Optional[float] = None
        #: spawn timestamps by replica index (monotonic) — the bench's
        #: spawn-to-first-answer criterion reads these
        self.spawn_times: Dict[int, float] = {}
        self._last_decision: Dict = {"action": "none", "reason": "startup",
                                     "t": time.monotonic()}
        self._last_signals: Dict = {}
        self.ticks_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = lockwitness.make_lock("autoscaler.state")
        # replica /statusz polls run concurrently: a tick must not stall
        # statusz_timeout_s x N sequentially exactly when the fleet is
        # overloaded and the scale-up is most urgent
        self._poll_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="dks-autoscale-poll")
        self._attach_metrics(proxy.metrics)
        proxy.attach_autoscaler(self)

    # -- observability -------------------------------------------------- #

    def _attach_metrics(self, registry) -> None:
        self._m_replicas = registry.gauge(
            "dks_autoscale_replicas",
            "Fleet composition by replica lifecycle state.",
            labelnames=("state",))
        self._m_replicas.set_function(
            lambda: {(state,): count for state, count
                     in self.proxy.replica_state_counts().items()})
        registry.gauge(
            "dks_autoscale_target_replicas",
            "Serving-intent replicas (ready + warming) the scaler is "
            "currently steering toward.").set_function(
            lambda: self._serving_intent())
        self._m_decisions = registry.counter(
            "dks_autoscale_decisions_total",
            "Scaler decisions by action and reason (hold rows count "
            "signals suppressed by cooldowns or bounds, not idle ticks).",
            labelnames=("action", "reason")).seed(
            ("scale_up", "burn_rate"), ("scale_up", "queue_wait"),
            ("scale_up", "rate_trend"), ("scale_up", "standby_replenish"),
            ("scale_down", "idle"),
            ("hold", "cooldown"), ("hold", "max_replicas"),
            ("hold", "min_replicas"))
        self._m_ticks = registry.counter(
            "dks_autoscale_ticks_total", "Scaler evaluation ticks.")
        self._m_replica_seconds = registry.counter(
            "dks_autoscale_replica_seconds_total",
            "Replica-seconds accumulated by lifecycle state (the "
            "provisioning cost the autoscaler exists to minimise); pod "
            "fleets accrue in PROCESS units — each pod-second costs its "
            "process count.",
            labelnames=("state",)).seed(
            ("ready",), ("warming",), ("draining",), ("standby",))

    def statusz_panel(self) -> Dict:
        """The ``/statusz`` autoscaler block (rendered by the proxy's
        component-detail table)."""

        now = time.monotonic()
        cfg = self.config
        # every scaler-thread-mutated signal (streaks, cooldown stamps,
        # tick count, draining book) reads under the SAME lock the tick
        # path writes under (DKS-C001/DKS-C002) — the panel must never
        # render a torn decision state
        with self._lock:
            last = dict(self._last_decision)
            signals = dict(self._last_signals)
            draining = {i: round(now - d["since"], 1)
                        for i, d in self._draining.items()}
            up_streak, down_streak = self._up_streak, self._down_streak
            last_up_t, last_down_t = self._last_up_t, self._last_down_t
            ticks_total = self.ticks_total
        up_cd = (max(0.0, cfg.up_cooldown_s - (now - last_up_t))
                 if last_up_t is not None else 0.0)
        down_cd = (max(0.0, cfg.down_cooldown_s - (now - last_down_t))
                   if last_down_t is not None else 0.0)
        return {
            "bounds": [cfg.min_replicas, cfg.max_replicas],
            "warm_standby": cfg.warm_standby,
            "states": self.proxy.replica_state_counts(),
            "serving_intent": self._serving_intent(),
            "last_decision": {"action": last["action"],
                              "reason": last["reason"],
                              "age_s": round(now - last["t"], 1)},
            "signals": signals,
            "up_streak": up_streak,
            "down_streak": down_streak,
            "cooldown_up_remaining_s": round(up_cd, 1),
            "cooldown_down_remaining_s": round(down_cd, 1),
            "draining_age_s": draining,
            "ticks_total": ticks_total,
            "alive": self._thread is not None and self._thread.is_alive(),
        }

    # -- signal gathering ----------------------------------------------- #

    def _serving_intent(self) -> int:
        counts = self.proxy.replica_state_counts()
        return counts.get("ready", 0) + counts.get("warming", 0)

    def _replica_detail(self, replica) -> Optional[Dict]:
        """One replica's ``/statusz?format=json`` ``detail`` block (queue
        depths, projected waits, in-flight) — ``None`` when unreachable
        or unparsable (no signal beats a wrong signal)."""

        conn = http.client.HTTPConnection(
            replica.host, replica.port,
            timeout=self.config.statusz_timeout_s)
        try:
            conn.request("GET", "/statusz?format=json")
            body = conn.getresponse().read()
            return json.loads(body).get("detail")
        except (OSError, http.client.HTTPException, ValueError):
            return None
        finally:
            conn.close()

    def capacity_hint(self, units: float) -> None:
        """Rescale the fleet-capacity EWMA for a known capacity change
        (``ReplicaManager`` calls this with the starting fleet size; the
        scaler itself calls it on every completed scale event)."""

        self.estimator.capacity_hint(units)
        self._hinted_ready = int(units)

    def _gather(self) -> Dict:
        """One tick's signal snapshot."""

        cfg = self.config
        store = self.proxy.health.store
        now_wall = time.time()
        rate_short = store.rate("dks_fanin_forwarded_total",
                                cfg.trend_window_short_s, now=now_wall)
        rate_long = store.rate("dks_fanin_forwarded_total",
                               cfg.trend_window_long_s, now=now_wall)
        breached = [s["name"] for s in self.proxy.health.slo_statuses()
                    if s["breached"]]
        ready = [r for r in self.proxy.replicas if r.state() == "ready"]
        queued_rows = 0
        per_replica_rates: List[float] = []
        max_replica_wait = 0.0
        rows_seen: Dict[int, float] = {}
        details = (list(self._poll_pool.map(self._replica_detail, ready))
                   if ready else [])
        for r, detail in zip(ready, details):
            if detail is None:
                continue
            queued_rows += sum((detail.get("queue_depths") or {}).values())
            rate = detail.get("service_rate_rows_per_s")
            if rate:
                per_replica_rates.append(float(rate))
            rows_total = detail.get("rows_served_total")
            if rows_total is not None:
                rows_seen[r.index] = float(rows_total)
            waits = detail.get("projected_wait_s") or {}
            wait = waits.get("interactive")
            if wait is not None:
                max_replica_wait = max(max_replica_wait, float(wait))
        # fleet-capacity EWMA: mean per-replica device rate x ready count,
        # folded in as one observation per tick.  The hint reconciliation
        # runs FIRST — rescaling after the observe would re-multiply a
        # sample that was already taken at the new fleet size
        n_ready = len(ready)
        if per_replica_rates and n_ready:
            if self._hinted_ready is None or n_ready != self._hinted_ready:
                # ready count moved — a warmed scale-up turned routable,
                # a drain landed, or something outside the scaler (a
                # crash, a supervisor restart): rescale the projection
                # the moment real capacity changed
                self.capacity_hint(n_ready)
            cap = (sum(per_replica_rates) / len(per_replica_rates)) * n_ready
            self.estimator.observe(max(1, int(cap)), 1.0)
        # served-rows DEMAND (rows/s): differentiate the replicas'
        # cumulative rows_served_total between ticks, summed over the
        # replicas present in both snapshots (membership-safe across
        # scale events).  Unit-compatible with the rows/s capacity EWMA —
        # the forwarded REQUEST rate is not, requests carry arbitrary
        # row counts
        now_mono = time.monotonic()
        demand = None
        if self._rows_prev is not None:
            dt = now_mono - self._rows_prev_t
            common = [i for i in rows_seen if i in self._rows_prev]
            if dt > 0 and common:
                delta = sum(max(0.0, rows_seen[i] - self._rows_prev[i])
                            for i in common)
                demand = delta / dt
        self._rows_prev, self._rows_prev_t = rows_seen, now_mono
        fleet_rate = self.estimator.rows_per_s()
        fleet_wait = (queued_rows / fleet_rate
                      if fleet_rate and queued_rows else 0.0)
        utilization = (demand / fleet_rate
                       if fleet_rate and demand is not None else None)
        return {
            "ready": n_ready,
            "breached_slos": breached,
            "queued_rows": int(queued_rows),
            "fleet_rate_rows_per_s": (round(fleet_rate, 2)
                                      if fleet_rate else None),
            "fleet_projected_wait_s": round(fleet_wait, 3),
            "max_replica_interactive_wait_s": round(max_replica_wait, 3),
            "demand_rows_per_s": (round(demand, 2)
                                  if demand is not None else None),
            "rate_short_rps": (round(rate_short, 2)
                               if rate_short is not None else None),
            "rate_long_rps": (round(rate_long, 2)
                              if rate_long is not None else None),
            "utilization": (round(utilization, 3)
                            if utilization is not None else None),
        }

    # -- decisions ------------------------------------------------------ #

    def _up_reason(self, sig: Dict) -> Optional[str]:
        cfg = self.config
        if sig["breached_slos"]:
            return "burn_rate"
        if (sig["fleet_projected_wait_s"] > cfg.queue_wait_up_s
                or sig["max_replica_interactive_wait_s"]
                > cfg.replica_wait_up_s):
            return "queue_wait"
        short, long_ = sig["rate_short_rps"], sig["rate_long_rps"]
        util = sig["utilization"]
        if (short is not None and long_ is not None and long_ > 0
                and util is not None
                and short >= cfg.trend_factor * long_
                and util >= cfg.trend_min_utilization):
            return "rate_trend"
        return None

    def _down_ok(self, sig: Dict) -> bool:
        cfg = self.config
        if sig["breached_slos"] or sig["queued_rows"] > 0:
            return False
        if sig["ready"] <= cfg.min_replicas:
            return False
        demand, fleet = sig["demand_rows_per_s"], sig["fleet_rate_rows_per_s"]
        if demand is None or not fleet or sig["ready"] < 1:
            return False
        # would one fewer replica serve the current rows/s demand at or
        # under the target utilization?  (fleet rate is for the CURRENT
        # size; demand is served rows, same units)
        reduced_capacity = fleet * (sig["ready"] - 1) / sig["ready"]
        return reduced_capacity > 0 and \
            demand <= cfg.down_utilization * reduced_capacity

    def _scale_up(self, reason: str, now: float) -> None:
        cfg = self.config
        counts = self.proxy.replica_state_counts()
        # the bound counts "down" too: a crashed replica is about to be
        # respawned by the supervisor, so spawning a replacement on top
        # would overshoot max_replicas the moment the prober readmits it
        committed = (counts.get("ready", 0) + counts.get("warming", 0)
                     + counts.get("down", 0))
        if committed >= cfg.max_replicas:
            self._m_decisions.inc(action="hold", reason="max_replicas")
            return
        # a warm standby is the fast path: activation is instant, and a
        # replacement standby warms in the background
        standby_idx = next(
            (r.index for r in self.proxy.replicas
             if r.standby and r.warm_ready and not r.retired), None)
        if standby_idx is not None:
            routable = self.proxy.activate_standby(standby_idx)
            logger.info("autoscale: activated standby replica %d (%s)%s",
                        standby_idx, reason,
                        "" if routable else " — prober will admit")
            self._flight.record("scale_up", reason=reason,
                                replica=standby_idx, standby_activated=True)
            self._m_decisions.inc(action="scale_up", reason=reason)
            self._replenish_standby()
        else:
            index = self.fleet.spawn_replica(standby=False)
            if index is None:
                return
            self.spawn_times[index] = time.monotonic()
            logger.info("autoscale: spawned replica %d (%s); pre-warming "
                        "through the DKS_WARMUP ladder", index, reason)
            self._flight.record("scale_up", reason=reason, replica=index,
                                standby_activated=False)
            self._m_decisions.inc(action="scale_up", reason=reason)
        with self._lock:
            self._last_up_t = now
            self._up_streak = 0
            self._last_decision = {"action": "scale_up", "reason": reason,
                                   "t": now}
        if standby_idx is not None:
            # an activated standby serves NOW — rescale the projection.
            # A spawned worker is only warming: it earns its hint when
            # the ready count actually moves (_gather reconciles), never
            # before it can serve a row
            counts = self.proxy.replica_state_counts()
            self.capacity_hint(max(1, counts.get("ready", 0)))

    def _replenish_standby(self) -> None:
        cfg = self.config
        counts = self.proxy.replica_state_counts()
        standbys = counts.get("standby", 0)
        total_live = (self._serving_intent() + standbys
                      + counts.get("down", 0))
        if standbys >= cfg.warm_standby or \
                total_live >= cfg.max_replicas + cfg.warm_standby:
            return
        index = self.fleet.spawn_replica(standby=True)
        if index is not None:
            self.spawn_times[index] = time.monotonic()
            self._m_decisions.inc(action="scale_up",
                                  reason="standby_replenish")
            self._flight.record("scale_up", reason="standby_replenish",
                                replica=index, standby_activated=False)

    def _scale_down(self, now: float) -> None:
        cfg = self.config
        ready = [r for r in self.proxy.replicas if r.state() == "ready"]
        if len(ready) <= cfg.min_replicas:
            self._m_decisions.inc(action="hold", reason="min_replicas")
            return
        # LIFO victim: the most recently added replica drains first, so
        # long-lived replicas keep their warm caches
        victim = max(ready, key=lambda r: r.index)
        self.proxy.start_drain(victim.index)
        with self._lock:
            self._draining[victim.index] = {"since": now, "idle_polls": 0}
        logger.info("autoscale: draining replica %d (idle scale-down)",
                    victim.index)
        self._flight.record("scale_down", reason="idle",
                            replica=victim.index)
        self._m_decisions.inc(action="scale_down", reason="idle")
        with self._lock:
            self._last_down_t = now
            self._down_streak = 0
            self._last_decision = {"action": "scale_down", "reason": "idle",
                                   "t": now}
        # the victim stopped taking NEW work the moment start_drain
        # flipped it to "draining" — the ready count already excludes it
        counts = self.proxy.replica_state_counts()
        self.capacity_hint(max(1, counts.get("ready", 0)))

    def _poll_draining(self, now: float) -> None:
        """Advance every in-progress drain: retire a victim once its
        queues AND in-flight batches have been empty for
        ``drain_settle_polls`` consecutive polls (or the drain timed
        out — its own ``server.stop()`` then answers stragglers with the
        retriable pre-dispatch 503)."""

        cfg = self.config
        # snapshot under the lock: statusz handlers iterate _draining
        # concurrently (DKS-C002); book dicts stay scaler-thread-private
        with self._lock:
            pending = list(self._draining.items())
        for index, book in pending:
            replica = self.proxy.replicas[index]
            forced = now - book["since"] > cfg.drain_timeout_s
            if not forced:
                detail = self._replica_detail(replica)
                if detail is None:
                    # unreachable THIS poll: one transient statusz
                    # timeout on a busy victim must not cut its queued
                    # work short — only a replica that stays dark for
                    # consecutive polls (crashed mid-drain) is forced;
                    # drain_timeout_s backstops everything else
                    book["misses"] = book.get("misses", 0) + 1
                    book["idle_polls"] = 0
                    if book["misses"] < 3:
                        continue
                    forced = True
                else:
                    book["misses"] = 0
                    queued = sum((detail.get("queue_depths") or {}).values())
                    inflight = detail.get("in_flight_batches", 0)
                    book["idle_polls"] = (book["idle_polls"] + 1
                                          if queued == 0 and inflight == 0
                                          else 0)
                    if book["idle_polls"] < cfg.drain_settle_polls:
                        continue
            drain_s = now - book["since"]
            with self._lock:
                del self._draining[index]
            try:
                self.fleet.retire_replica(index)
            except Exception:
                logger.exception("autoscale: retiring replica %d failed",
                                 index)
                self.proxy.finish_drain(index)
            logger.info("autoscale: replica %d drained and retired in "
                        "%.1fs%s", index, drain_s,
                        " (forced by timeout)" if forced else "")
            self._flight.record("drain_complete", replica=index,
                                drain_s=round(drain_s, 2),
                                forced=bool(forced))

    # -- the loop ------------------------------------------------------- #

    def tick(self) -> Dict:
        """One deterministic control step (the thread calls this every
        ``interval_s``; tests call it directly).  Returns the signal
        snapshot it acted on."""

        if self._faults is not None:
            action = self._faults.fire("scaler.tick", crash_scope="thread")
            if action == "crash":
                # thread-scoped: the scaler dies, the fleet serves on at
                # its current size (the chaos invariant)
                raise _ScalerCrashed("injected crash at scaler.tick")
        now = time.monotonic()
        cfg = self.config
        with self._lock:
            self.ticks_total += 1
        self._m_ticks.inc()
        # replica-seconds accrue by state every tick, over the REAL time
        # since the last accrual — a tick stalled on statusz timeouts
        # still integrates the full elapsed provisioning cost
        accrue_s = (now - self._accrual_t if self._accrual_t is not None
                    else cfg.interval_s)
        self._accrual_t = now
        for state, count in self.proxy.replica_state_counts().items():
            if count and state in ("ready", "warming", "draining",
                                   "standby"):
                self._m_replica_seconds.inc(
                    count * accrue_s * self.unit_processes, state=state)
        self._poll_draining(now)
        sig = self._gather()
        with self._lock:
            self._last_signals = sig
        up_reason = self._up_reason(sig)
        if up_reason is not None:
            # streaks and cooldown stamps are panel-visible: mutate and
            # read under the lock (DKS-C001), act after release —
            # _scale_up re-acquires it for its own decision write
            with self._lock:
                self._up_streak += 1
                self._down_streak = 0
                fire = self._up_streak >= cfg.up_ticks
                cooling = (self._last_up_t is not None
                           and now - self._last_up_t < cfg.up_cooldown_s)
            if fire:
                if cooling:
                    self._m_decisions.inc(action="hold", reason="cooldown")
                else:
                    self._scale_up(up_reason, now)
            return sig
        with self._lock:
            self._up_streak = 0
        # down only from a fully settled fleet: anything warming or
        # draining means the last action has not landed yet
        counts = self.proxy.replica_state_counts()
        settled = not counts.get("warming") and not self._draining
        if settled and self._down_ok(sig):
            with self._lock:
                self._down_streak += 1
                fire = self._down_streak >= cfg.down_ticks
                cooling = (self._last_down_t is not None and
                           now - self._last_down_t < cfg.down_cooldown_s)
            if fire:
                if cooling:
                    self._m_decisions.inc(action="hold", reason="cooldown")
                else:
                    self._scale_down(now)
        else:
            with self._lock:
                self._down_streak = 0
        # keep the standby pool full even in steady state (covers the
        # initial fill when start() raced replica startup)
        if counts.get("standby", 0) < cfg.warm_standby and settled:
            self._replenish_standby()
        return sig

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except _ScalerCrashed:
                logger.error("autoscaler crashed (injected); fleet stays "
                             "at its current size")
                return
            except Exception:
                # one bad tick (a torn statusz, a race on a dying
                # replica) must not kill elasticity for the process
                logger.exception("autoscaler tick failed")

    def start(self) -> "Autoscaler":
        # fill the warm-standby pool up front so the first peak activates
        # instead of spawning
        for _ in range(self.config.warm_standby):
            self._replenish_standby()
        self._thread = threading.Thread(target=self._loop,
                                        name="dks-autoscaler", daemon=True)
        self._thread.start()
        logger.info("autoscaler started: bounds [%d, %d], %d warm standby",
                    self.config.min_replicas, self.config.max_replicas,
                    self.config.warm_standby)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._poll_pool.shutdown(wait=False)
