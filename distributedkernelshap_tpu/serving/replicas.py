"""Replica-per-chip serving: crash-isolated single-device server processes
behind a tiny fan-in proxy.

The reference gets N crash-isolated replicas for free from Ray Serve
(``explainers/wrappers.py:10-88`` backends, ``serve_explanations.py:59-65``
``num_replicas``, restart via ``cluster/ray_cluster.yaml:63``).  Round 4's
single-process pipeline recovered the *fault behaviour* (watchdog fast
errors + orchestrator restart) but a poisoned native call still took down
every in-flight request on the host (VERDICT r4 missing #3).  On a
multi-chip host (v5e-8) the TPU-native answer is one server PROCESS per
chip — each owns its device and its compiled explain function — behind
this fan-in:

* **Routing** — round-robin over live replicas.  A replica whose
  *connection* fails before the request is sent is marked dead and the
  request retried on the next live replica (it was never processed — the
  retry cannot double-execute); a failure *mid-request* surfaces to that
  client as a 502 naming the replica (the request may have reached the
  device — exactly the reference's crashed-replica semantics, where
  in-flight requests die with their actor and only those).
* **Recovery** — a prober re-checks dead replicas' ``/healthz`` and
  returns them to rotation; :class:`ReplicaManager` additionally restarts
  exited worker processes (the k8s-probe restart loop, in-process).
* **Device pinning** — each worker process sees ONE chip
  (``TPU_VISIBLE_CHIPS=<k>`` on TPU hosts; see ``replica_worker.py``), so
  a crash loses one chip's in-flight work, never the host's.

Stdlib-only, same as the rest of the serving stack: the proxy is a
``ThreadingHTTPServer`` whose handler threads forward with
``http.client`` — no event loop to wedge, no dependency to pin.
"""

import http.client
import json
import logging
import math
import os
import queue
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

import distributedkernelshap_tpu.observability.tracing as _tracing
from distributedkernelshap_tpu.observability import fleet as _fleet
from distributedkernelshap_tpu.observability.contprof import (
    contprof,
    merge_collapsed,
)
from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.observability.quality import (
    merge_quality_pages,
    stub_doc as quality_stub_doc,
)
from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.metrics import (
    DEFAULT_EXEMPLAR_SLOTS,
    MetricsRegistry,
    parse_exposition,
)
from distributedkernelshap_tpu.observability.slo import default_proxy_slos
from distributedkernelshap_tpu.observability.statusz import (
    HealthEngine,
    statusz_response,
)
from distributedkernelshap_tpu.resilience.hedging import (
    HedgePolicy,
    LatencyQuantiles,
)
from distributedkernelshap_tpu.resilience.supervisor import (
    ReplicaSupervisor,
    RestartPolicy,
)

logger = logging.getLogger(__name__)


class _ProxyHTTPServer(ThreadingHTTPServer):
    request_queue_size = 1024
    daemon_threads = True


class _Replica:
    """Fan-in-side state for one backend replica.

    Besides liveness (``alive`` — owned by the prober/supervisor/failed
    connects, exactly as before), a replica carries the autoscaler's
    lifecycle flags:

    * ``warming`` — the prober saw the warmup ladder's distinct 503
      ``{"status": "warming"}``: started, compiling, not yet routable.
    * ``standby`` — a warm-standby pool member: fully probed-ready
      (``warm_ready``) but held OUT of rotation until the scaler
      activates it (activation is then instant instead of a spawn+warm).
    * ``draining`` — scale-down victim: no NEW forwards are routed to it,
      but in-flight requests (and its queued work) still answer normally.
    * ``retired`` — drained and gone; never probed, never routed.
    """

    def __init__(self, index: int, host: str, port: int):
        self.index = index
        self.host = host
        self.port = port
        self.alive = True
        self.warming = False
        self.standby = False
        self.warm_ready = False
        self.draining = False
        self.retired = False
        # monotonic time until which this replica has declared itself
        # saturated (it answered 429 reason=queue_full): alive, just not
        # worth forwarding to.  Keyed by the request's priority class —
        # the replica's queue bounds are per class, so a batch-class flood
        # filling batch queues must not mark the replica saturated for
        # interactive traffic it still admits.
        self.saturated_until: Dict[str, float] = {}

    def routable(self) -> bool:
        """Eligible for NEW forwards.  ``alive`` alone is not enough: a
        draining victim must finish its in-flight work without taking on
        more, and a standby is deliberately held out of rotation."""

        return (self.alive and not self.draining and not self.retired
                and not self.standby)

    def state(self) -> str:
        """The autoscaler's one-word lifecycle view (feeds
        ``dks_autoscale_replicas{state=}`` and ``/statusz``)."""

        if self.retired:
            return "retired"
        if self.draining:
            return "draining"
        if self.standby:
            return "standby"
        if self.alive:
            return "ready"
        return "warming" if self.warming else "down"

    def saturated_for(self, klass: str) -> float:
        """Backoff expiry for one class (0.0 when not backed off)."""

        return self.saturated_until.get(klass, 0.0)

    def saturated_any(self) -> float:
        # .copy() is a single C-level op (atomic under the GIL): handler
        # threads insert new class keys concurrently, and iterating the
        # live dict could raise "dictionary changed size during iteration"
        return max(self.saturated_until.copy().values(), default=0.0)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class FanInProxy:
    """Round-robin HTTP fan-in over N replica servers (see module doc)."""

    def __init__(self, targets: Sequence[Tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 600.0,
                 probe_interval_s: float = 1.0,
                 trust_client_header: bool = False,
                 hedge_policy: Optional[HedgePolicy] = None,
                 health_interval_s: float = 1.0,
                 slos=None, alert_rules=None, alert_sinks=None):
        #: whether a client-supplied ``X-DKS-Client`` passes through.  Off
        #: by default: the proxy is the trust boundary, and an untrusted
        #: client choosing its own rate-limit key defeats per-client
        #: limiting (a fresh key per request = a fresh full token bucket).
        #: Enable only when an authenticated edge in front of the proxy
        #: sets the header.
        self.trust_client_header = trust_client_header
        self.replicas = [_Replica(i, h, p) for i, (h, p) in enumerate(targets)]
        if not self.replicas:
            raise ValueError("FanInProxy needs at least one replica target")
        self.host, self.port = host, port
        self.request_timeout_s = request_timeout_s
        self.probe_interval_s = probe_interval_s
        self._rr_lock = lockwitness.make_lock("proxy.rr")
        self._rr = 0
        # per-thread keep-alive connections to each replica (handler and
        # hedge threads are long-lived pool threads): without reuse every
        # forwarded request paid a TCP handshake — the proxy-side half of
        # the per-request plumbing the streaming hot path removes
        self._fwd_tls = threading.local()
        # every dks_fanin_* series lives on the shared registry (one
        # renderer; per-metric locks make increments from hedge/handler
        # threads atomic — these used to be bare dict/int updates)
        self.metrics = MetricsRegistry()
        self._flight = flightrec()
        self._tracer = _tracing.tracer()
        reg = self.metrics
        self._m_forwarded = reg.counter(
            "dks_fanin_forwarded_total",
            "Requests forwarded to a replica and answered.")
        self._m_replica_errors = reg.counter(
            "dks_fanin_replica_errors_total",
            "Requests surfaced as a replica's mid-request failure.")
        self._m_retried_connects = reg.counter(
            "dks_fanin_retried_connects_total",
            "Connect failures retried on another replica.")
        self._m_503_demotions = reg.counter(
            "dks_fanin_replica_503_demotions_total",
            "Replicas demoted after answering 503 (alive but "
            "self-declared unserviceable).")
        self._m_sheds = reg.counter(
            "dks_fanin_sheds_total",
            "Requests shed at the proxy with 429 because every live "
            "replica reported saturation.")
        self._m_hedges = reg.counter(
            "dks_fanin_hedges_total",
            "Requests re-dispatched to a second replica after the hedge "
            "delay.")
        self._m_hedge_wins = reg.counter(
            "dks_fanin_hedge_wins_total",
            "Hedged requests whose hedge answered first with a success.")
        # end-to-end latency by priority class, observed at the proxy for
        # every 200 it returns (hedged or not) — the histogram the
        # autoscaler's interactive-latency SLO burns against, and the
        # fleet-level twin of the replica-side
        # dks_serve_class_latency_seconds.  Bucket bounds match the
        # server's LATENCY_BUCKETS_S (slo.CLASS_LATENCY_TARGETS requires
        # every threshold at or below the largest finite bucket).
        self._m_class_latency = reg.histogram(
            "dks_fanin_class_latency_seconds",
            "Proxy-observed request latency of successful /explain "
            "answers by priority class.",
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
            labelnames=("class",),
            exemplar_slots=DEFAULT_EXEMPLAR_SLOTS)
        # federated fleet view (/fleetz, /metrics?federate=1): scrape
        # accounting — per-replica failures already have their own
        # attribution, so these stay unlabeled (bounded by construction)
        self._m_fleet_scrapes = reg.counter(
            "dks_fleet_scrapes_total",
            "Federated scrape sweeps served (/fleetz and "
            "/metrics?federate=1 each scrape every live replica).")
        self._m_fleet_scrape_errors = reg.counter(
            "dks_fleet_scrape_errors_total",
            "Replica scrape failures during federated sweeps (the "
            "replica's samples are missing from that rollup).")
        self._m_fleet_scraped = reg.gauge(
            "dks_fleet_replicas_scraped",
            "Replicas whose exposition the last federated sweep "
            "merged.")
        # the always-on sampling profiler's self-metering (shared
        # process-wide sampler; the proxy exposes it like any server)
        contprof().attach_metrics(reg)
        reg.gauge("dks_fanin_replica_up", "Replica liveness by index.",
                  labelnames=("replica", "address")).set_function(
            lambda: {(str(r.index), r.address): int(r.alive)
                     for r in self.replicas})
        reg.gauge("dks_fanin_replica_saturated",
                  "Replica currently backing off after a 429.",
                  labelnames=("replica", "address")).set_function(
            lambda: {(str(r.index), r.address):
                     int(time.monotonic() < r.saturated_any())
                     for r in self.replicas})
        # per-replica failure attribution (timeouts, mid-request failures,
        # 503 demotions) — previously a bare int += on _Replica racing
        # across hedge threads
        self._m_replica_failures = reg.counter(
            "dks_fanin_replica_failures_total",
            "Failures attributed to one replica (timeouts, mid-request "
            "failures, 503 demotions).",
            labelnames=("replica", "address")).seed(
            *[(str(r.index), r.address) for r in self.replicas])
        # SLO health engine behind /statusz (same shape as the server's;
        # built here so dks_slo_*/dks_alerts_* register with the rest)
        self.health = HealthEngine(
            reg, component="proxy",
            slos=default_proxy_slos() if slos is None else slos,
            rules=alert_rules, sinks=alert_sinks, flight=self._flight,
            interval_s=health_interval_s,
            spark_names=("dks_fanin_forwarded_total",
                         "dks_fanin_replica_errors_total",
                         "dks_fanin_hedges_total",
                         "dks_fanin_sheds_total"))
        # replica supervisor, when a ReplicaManager runs one: its restart
        # stats join the /statusz replica-liveness block; ditto the
        # autoscaler's panel once one attaches
        self._supervisor = None
        self._autoscaler = None
        #: tail-latency hedging (``resilience/hedging.py``).  ``None``
        #: (default) disables it — behaviour is then byte-identical to the
        #: pre-hedging proxy.  Safe to enable because /explain is
        #: idempotent (deterministic, content-addressed): the proxy
        #: returns exactly one answer and discards the hedge loser, whose
        #: payload would have been bit-identical anyway.
        self.hedge_policy = hedge_policy
        self._latency = LatencyQuantiles()
        # shared pool for racing passes (workers spawn lazily on submit):
        # hedging must not pay a thread create/teardown per request on
        # top of the server's handler thread
        self._hedge_pool = (ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="dks-hedge")
            if hedge_policy is not None else None)
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------ #

    def _observe_latency(self, klass: str, seconds: float,
                         exemplar: Optional[str] = None) -> None:
        """One successful answer's end-to-end latency: feeds the hedge
        policy's sliding quantiles AND the per-class histogram the
        autoscaler's SLO burn rate reads; ``exemplar`` (the request's
        trace id, when tracing is on) lands in the observation's bucket
        so a proxy-side SLO breach links to a concrete trace."""

        self._latency.observe(klass, seconds)
        self._m_class_latency.observe(seconds, exemplar=exemplar,
                                      **{"class": klass})

    # -- federated fleet view (/fleetz, /metrics?federate=1) ------------ #

    def _fleet_scrape_pool(self) -> ThreadPoolExecutor:
        """Lazy small pool for federated sweeps: replicas are scraped
        CONCURRENTLY so one slow member costs the sweep one timeout, not
        the sum over the fleet (the /fleetz handler — which the
        autoscaler may poll — blocks for the sweep's duration).  Pooled
        forward connections are per-thread, so the fixed worker set also
        keeps keep-alive sockets warm across sweeps."""

        pool = getattr(self, "_fleet_pool", None)
        if pool is None:
            pool = self._fleet_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="dks-fleet")
        return pool

    def _scrape_replicas(self, timeout_s: float = 5.0,
                         with_debugz: bool = False):
        """One federated sweep: fetch ``/metrics`` (and, for the rollup,
        ``/debugz`` exemplars) from every scrapable replica (alive,
        draining or standby — a drain victim's tallies still belong in
        the rollup; down/retired replicas are skipped), concurrently
        over the pooled connections.  Returns ``({replica_index: body},
        {replica_index: meta}, {replica_index: exemplars})``; failures
        are counted and the replica simply missing from that sweep."""

        targets = [r for r in list(self.replicas)
                   if not r.retired and (r.alive or r.draining or r.standby)]
        meta = {str(r.index): {"address": r.address, "state": r.state(),
                               "scraped": False} for r in targets}
        pages: Dict[str, bytes] = {}
        exemplars: Dict[str, List[Dict]] = {}

        def scrape(r):
            key = str(r.index)
            try:
                status, body, _ = self._forward("GET", "/metrics", b"", r,
                                                timeout_s=timeout_s)
            except (OSError, http.client.HTTPException):
                self._m_fleet_scrape_errors.inc()
                return
            if status != 200:
                self._m_fleet_scrape_errors.inc()
                return
            pages[key] = body
            meta[key]["scraped"] = True
            if not with_debugz:
                return
            try:
                status, body, _ = self._forward("GET", "/debugz", b"", r,
                                                timeout_s=timeout_s)
                if status == 200:
                    doc = json.loads(body)
                    if isinstance(doc.get("exemplars"), list):
                        exemplars[key] = doc["exemplars"]
            except (OSError, http.client.HTTPException, ValueError):
                pass  # exemplars are garnish; the rollup stands without
        if targets:
            list(self._fleet_scrape_pool().map(scrape, targets))
        self._m_fleet_scrapes.inc()
        self._m_fleet_scraped.set(len(pages))
        return pages, meta, exemplars

    def federated_metrics(self) -> str:
        """The ``/metrics?federate=1`` page: every scrapable replica's
        exposition merged into one compliant page with a ``replica``
        label (``observability/fleet.merge_expositions``; merge rules —
        incl. conflicting-TYPE handling — documented there).  The
        proxy's OWN series stay on the plain ``/metrics``."""

        pages, meta, _ = self._scrape_replicas()
        text, report = _fleet.merge_expositions(
            {k: pages[k].decode("utf-8", errors="replace")
             for k in sorted(pages, key=int)})
        for fam, replica, kind in report["type_conflicts"]:
            logger.warning("federate: replica %s declares %s as %s, "
                           "conflicting with the merged page; its "
                           "samples were dropped", replica, fam, kind)
        for replica, error in report["parse_failures"]:
            # same operator signal as a failed scrape: the replica's
            # samples are missing from this page
            self._m_fleet_scrape_errors.inc()
            logger.warning("federate: replica %s served an unparseable "
                           "exposition (%s); its samples were dropped",
                           replica, error)
        return text

    def federated_profile(self, timeout_s: float = 5.0) -> str:
        """The ``/profilez?federate=1`` page: every scrapable replica's
        collapsed-stack profile fetched concurrently over the fleet
        scrape pool and merged by summing per-stack sample counts
        (``observability/contprof.merge_collapsed``).  A replica that
        fails to answer is simply missing from the merge, counted like
        any other federated scrape failure."""

        targets = [r for r in list(self.replicas)
                   if not r.retired and (r.alive or r.draining
                                         or r.standby)]
        pages: Dict[str, str] = {}

        def scrape(r):
            try:
                status, body, _ = self._forward(
                    "GET", "/profilez?format=collapsed", b"", r,
                    timeout_s=timeout_s)
            except (OSError, http.client.HTTPException):
                self._m_fleet_scrape_errors.inc()
                return
            if status != 200:
                self._m_fleet_scrape_errors.inc()
                return
            pages[str(r.index)] = body.decode("utf-8", errors="replace")
        if targets:
            list(self._fleet_scrape_pool().map(scrape, targets))
        self._m_fleet_scrapes.inc()
        return merge_collapsed(
            [pages[k] for k in sorted(pages, key=int)])

    def federated_quality(self, timeout_s: float = 5.0) -> str:
        """The ``/qualityz?federate=1`` page: every scrapable replica's
        quality document fetched concurrently over the fleet scrape pool
        and folded (``observability/quality.merge_quality_pages`` —
        counters sum, repro rings concatenate under the bound, per-tenant
        shadow/canary sections keep the worst error).  Same failure
        accounting as the flamegraph federation: an unanswering replica
        is missing from the fold and counted as a scrape error."""

        targets = [r for r in list(self.replicas)
                   if not r.retired and (r.alive or r.draining
                                         or r.standby)]
        pages: Dict[str, str] = {}

        def scrape(r):
            try:
                status, body, _ = self._forward(
                    "GET", "/qualityz", b"", r, timeout_s=timeout_s)
            except (OSError, http.client.HTTPException):
                self._m_fleet_scrape_errors.inc()
                return
            if status != 200:
                self._m_fleet_scrape_errors.inc()
                return
            pages[str(r.index)] = body.decode("utf-8", errors="replace")
        if targets:
            list(self._fleet_scrape_pool().map(scrape, targets))
        self._m_fleet_scrapes.inc()
        return merge_quality_pages(
            [pages[k] for k in sorted(pages, key=int)])

    def fleet_rollup(self) -> Dict:
        """The ``/fleetz`` document: per-tenant cost rollups summed over
        one fresh sweep of the fleet's ``/metrics`` + ``/debugz`` trace
        exemplars, schema in ``observability/fleet.fleet_rollup`` /
        docs/OBSERVABILITY.md.  Exposed as a method so the autoscaler
        (or an EDF-packing policy) can consume the same rollup the
        operator sees."""

        pages, meta, exemplars = self._scrape_replicas(with_debugz=True)
        parsed: Dict[str, Dict] = {}
        for key, body in pages.items():
            try:
                parsed[key] = parse_exposition(
                    body.decode("utf-8", errors="replace"))
            except ValueError:
                self._m_fleet_scrape_errors.inc()
                meta[key]["scraped"] = False
        return _fleet.fleet_rollup(parsed, exemplars=exemplars,
                                   replica_meta=meta)

    # -- elastic membership (serving/autoscaler.py) --------------------- #

    def add_target(self, host: str, port: int,
                   standby: bool = False,
                   index: Optional[int] = None) -> int:
        """Register a NEW replica address mid-run (the autoscaler's
        scale-up path; construction-time targets come via ``targets``).
        The replica starts OUT of rotation (``alive=False``): life is
        declared only by the prober, which readmits it the moment its
        ``/healthz`` answers 200 — i.e. the instant the warmup ladder
        finishes.  With ``standby=True`` the prober instead marks it
        ``warm_ready`` and holds it out of rotation until
        :meth:`activate_standby`.  Returns the replica index.

        A retired slot is RECYCLED rather than left to accumulate: the
        first retired replica's index is reused for the new address
        (``index=`` pins a specific retired slot — ``ReplicaManager``
        passes its own reused process slot so the two index spaces stay
        aligned), which bounds the rotation, the prober's scan and the
        per-index metric label sets at the fleet's high-water mark
        instead of growing by one dead entry per scale cycle."""

        with self._rr_lock:
            if index is not None:
                replica = self.replicas[index]
                if not replica.retired:
                    raise ValueError(
                        f"replica slot {index} is not retired (state "
                        f"{replica.state()}); only retired slots can be "
                        "reused")
            else:
                replica = next((r for r in self.replicas if r.retired),
                               None)
            if replica is not None:
                index = replica.index
                replica.host, replica.port = host, int(port)
                replica.retired = False
                replica.draining = False
                replica.warm_ready = False
                replica.saturated_until.clear()
            else:
                index = len(self.replicas)
                replica = _Replica(index, host, port)
                self.replicas.append(replica)
            replica.alive = False
            replica.warming = True  # until the prober says otherwise
            replica.standby = bool(standby)
        # seed the per-replica failure series so the new label combo
        # renders at 0 like the construction-time ones
        self._m_replica_failures.seed((str(index), replica.address))
        logger.info("fan-in: added replica %d at %s%s (awaiting prober)",
                    index, replica.address,
                    " as standby" if standby else "")
        return index

    def activate_standby(self, index: int) -> bool:
        """Promote a warm standby into rotation.  If the prober has
        already verified it ready (``warm_ready``), admission is
        immediate — the prober's last verdict is what standby-warmth
        MEANS, so this does not usurp the prober's ownership of life;
        otherwise the flag is cleared and the prober admits it on its
        next 200.  Returns whether the replica is routable right away."""

        r = self.replicas[index]
        r.standby = False
        if r.warm_ready and not r.retired:
            r.alive = True
            return True
        return False

    def start_drain(self, index: int) -> None:
        """Take one replica out of NEW-forward rotation while its queued
        and in-flight work keeps answering (scale-down's first half).
        The replica's own scheduler finishes what it holds; anything it
        503s during final shutdown is pre-dispatch and fails over."""

        self.replicas[index].draining = True

    def finish_drain(self, index: int) -> None:
        """Retire a drained replica for good: never probed, never routed
        again (its index stays — indices are identities here)."""

        r = self.replicas[index]
        r.draining = False
        r.retired = True
        r.alive = False
        r.warm_ready = False
        r.warming = False

    def replica_state_counts(self) -> Dict[str, int]:
        """``{state: count}`` over every registered replica — the
        autoscaler's ``dks_autoscale_replicas{state=}`` feed."""

        counts = {"ready": 0, "warming": 0, "draining": 0, "standby": 0,
                  "down": 0, "retired": 0}
        for r in self.replicas:
            counts[r.state()] = counts.get(r.state(), 0) + 1
        return counts

    def _pick(self, exclude: set) -> Optional[_Replica]:
        """Next live replica after the round-robin cursor, skipping
        ``exclude`` (replicas already tried for this request)."""

        with self._rr_lock:
            n = len(self.replicas)
            for step in range(n):
                r = self.replicas[(self._rr + step) % n]
                if r.routable() and r.index not in exclude:
                    self._rr = (self._rr + step + 1) % n
                    return r
        return None

    def _fresh_connection(self, replica: _Replica,
                          timeout_s: float) -> http.client.HTTPConnection:
        """Connect a new socket to one replica.  Short CONNECT timeout
        regardless of the request budget: a wedged replica with a full
        listen backlog neither accepts nor refuses — without this a client
        request would stall the full request_timeout_s inside connect()
        while healthy replicas idle."""

        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=5.0)
        try:
            conn.connect()
        except OSError:
            conn.close()
            raise _ConnectFailed(replica)
        conn.sock.settimeout(timeout_s)
        return conn

    def _forward(self, method: str, path: str, body: bytes,
                 replica: _Replica,
                 timeout_s: Optional[float] = None,
                 headers: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """One forwarded request over this thread's pooled keep-alive
        connection; raises on transport failure.  Separating connect from
        send lets the caller distinguish never-processed (safe to retry)
        from possibly-processed (must surface).  Returns ``(status,
        payload, response_headers)`` — the headers carry the replica's
        ``Retry-After`` on a 429 and its ``Content-Type`` (binary wire
        responses must reach the client labelled as such).

        Connections persist per (handler thread, replica) and fall back to
        a fresh socket only when the pooled one fails
        (``HTTPException``/``ConnectionError``/``OSError`` — typically a
        replica restart or an idle keep-alive the peer closed).  The
        single fresh-socket retry after a stale-reuse failure cannot
        corrupt results: explains are deterministic and content-addressed
        (the same property hedging already relies on), so a double
        execution produces a bit-identical payload.  A ``socket.timeout``
        is never retried here — slow is not stale, and the caller maps it
        to 504."""

        timeout = timeout_s or self.request_timeout_s
        send_headers = {}
        if headers:
            send_headers.update(headers)
        send_headers.setdefault("Content-Type", "application/json")
        conns = getattr(self._fwd_tls, "conns", None)
        if conns is None:
            conns = self._fwd_tls.conns = {}
        key = (replica.host, replica.port)
        conn = conns.get(key)
        reused = conn is not None and conn.sock is not None
        if not reused:
            conn = conns[key] = self._fresh_connection(replica, timeout)
        else:
            conn.sock.settimeout(timeout)
        try:
            conn.request(method, path, body=body, headers=send_headers)
            resp = conn.getresponse()
            return resp.status, resp.read(), dict(resp.getheaders())
        except socket.timeout:
            conns.pop(key, None)
            conn.close()
            raise
        except (http.client.HTTPException, ConnectionError, OSError):
            conns.pop(key, None)
            conn.close()
            if not reused:
                raise
            # the pooled socket went stale under us: one fresh-socket
            # retry before classifying the replica as failed
            conn = conns[key] = self._fresh_connection(replica, timeout)
            try:
                conn.request(method, path, body=body, headers=send_headers)
                resp = conn.getresponse()
                return resp.status, resp.read(), dict(resp.getheaders())
            except (http.client.HTTPException, ConnectionError, OSError):
                conns.pop(key, None)
                conn.close()
                raise

    @staticmethod
    def _retry_after_s(resp_headers: Dict[str, str], payload: bytes) -> float:
        """A 429's backoff hint via the shared wire parser
        (``client.parse_retry_after``), floored at 0.1 s, 1 s default."""

        from distributedkernelshap_tpu.serving.client import parse_retry_after

        hint = parse_retry_after(resp_headers, payload)
        return max(0.1, hint) if hint is not None else 1.0

    @staticmethod
    def _priority_class(headers: Optional[Dict[str, str]]) -> str:
        # saturation/hedging state is tracked per priority class (replica
        # queue bounds are per class).  A missing header is normalised to
        # "interactive" — the server's default default_class — so
        # headerless and explicitly-interactive traffic share one backoff
        # key instead of burning a round trip each to learn the same 429.
        # (A deployment overriding default_class should have clients send
        # the header.)
        for k, v in (headers or {}).items():
            if k.lower() == "x-dks-priority":
                return v.strip().lower()
        return "interactive"

    def handle_explain(self, method: str, body: bytes,
                       headers: Optional[Dict[str, str]] = None
                       ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one /explain request; never raises.  ``headers`` are the
        client's scheduling headers (priority class, deadline, client key),
        forwarded verbatim so the replica's scheduler and admission control
        see the same SLO the client declared.  With :attr:`hedge_policy`
        set, a request still unanswered past the class's latency quantile
        is re-dispatched to a second replica and the first answer wins
        (see ``resilience/hedging.py`` for why that is safe here)."""

        klass = self._priority_class(headers)
        tr = self._tracer
        root = None
        if tr.enabled:
            # the proxy's root span parents to the client's context (if it
            # sent X-DKS-Trace) so one trace id follows the request from
            # client through proxy into the replica
            root = tr.begin(
                "proxy.request",
                parent=_tracing.parse_trace_header(
                    _tracing.header_get(headers)),
                klass=klass)
        result: Tuple[int, bytes, Dict[str, str]] = (500, b"", {})
        try:
            if self.hedge_policy is None:
                t0 = time.monotonic()
                result = self._route_explain(method, body, headers, klass,
                                             span_parent=root)
                if result[0] == 200:
                    self._observe_latency(
                        klass, time.monotonic() - t0,
                        exemplar=root.trace_id if root is not None
                        else None)
            else:
                result = self._handle_hedged(method, body, headers, klass,
                                             root=root)
            return result
        finally:
            if root is not None:
                tr.end(root, status=result[0])

    def _handle_hedged(self, method: str, body: bytes,
                       headers: Optional[Dict[str, str]], klass: str,
                       root=None) -> Tuple[int, bytes, Dict[str, str]]:
        """Hedged routing: dispatch the primary, wait the policy delay,
        then race one hedge on a replica the primary has not touched.

        The proxy returns exactly ONE answer; the loser's response is
        discarded.  Double execution cannot double-count or diverge:
        explanations are deterministic and content-addressed (the PR-1
        result-cache key), so both copies produce bit-identical payloads
        and `forwarded_total` moves once per CLIENT request (inside
        ``_route_explain``, for whichever copy returns its answer)."""

        results: "queue.Queue" = queue.Queue()
        primary_tried: List[int] = []  # list: atomic appends, safe snapshot

        def run(slot: str, exclude):
            t0 = time.monotonic()
            # forward_sink defers the forwarded_total increment to the
            # winner below: the counter must move once per CLIENT request
            # (counting the answer the client actually received), never
            # once per racing copy
            fwd: List[int] = []
            try:
                res = self._route_explain(
                    method, body, headers, klass, tried=set(exclude),
                    record=primary_tried if slot == "primary" else None,
                    forward_sink=fwd, span_parent=root, slot=slot)
            except Exception as e:
                # a dead racing pass MUST still report in: both passes
                # dying silently would park this handler on an untimed
                # results.get() forever
                logger.exception("hedged routing pass failed")
                res = (500, json.dumps(
                    {"error": f"proxy routing failure: {e}"}).encode(), {})
            results.put((slot, res, time.monotonic() - t0, bool(fwd)))

        self._hedge_pool.submit(run, "primary", ())
        delay = self.hedge_policy.delay_for(self._latency, klass)
        hedged = False
        try:
            slot, res, lat, fwd = results.get(timeout=delay)
        except queue.Empty:
            exclude = list(primary_tried)
            if not any(r.routable() and r.index not in exclude
                       for r in self.replicas):
                # nowhere to hedge onto: just wait the primary out
                slot, res, lat, fwd = results.get()
            else:
                hedged = True
                self._m_hedges.inc()
                self._flight.record("hedge", klass=klass,
                                    excluded=list(exclude))
                self._hedge_pool.submit(run, "hedge", exclude)
                slot, res, lat, fwd = results.get()
                if res[0] != 200:
                    # first answer is an error while the other copy is
                    # still in flight: prefer a 200, else a genuine
                    # replica answer over a proxy-synthesized error (the
                    # more informative of two failures).  Bounded:
                    # _route_explain's transport timeouts guarantee the
                    # second answer arrives.
                    try:
                        slot2, res2, lat2, fwd2 = results.get(
                            timeout=self.request_timeout_s + 10.0)
                        if res2[0] == 200 or (fwd2 and not fwd):
                            slot, res, lat, fwd = slot2, res2, lat2, fwd2
                    except queue.Empty:
                        pass
        if fwd:  # a replica answered the winning copy (any status)
            self._m_forwarded.inc()
        if hedged and slot == "hedge" and res[0] == 200:
            self._m_hedge_wins.inc()
            self._flight.record("hedge_win", klass=klass)
        if res[0] == 200:
            self._observe_latency(klass, lat,
                                  exemplar=root.trace_id if root is not None
                                  else None)
        return res

    def _replica_failed(self, replica: _Replica) -> None:
        """Per-replica failure attribution on the registry's atomic
        counters (these used to be bare ``int +=`` racing across hedge
        threads)."""

        self._m_replica_failures.inc(replica=str(replica.index),
                                     address=replica.address)

    def _route_explain(self, method: str, body: bytes,
                       headers: Optional[Dict[str, str]], klass: str,
                       tried: Optional[set] = None,
                       record: Optional[List[int]] = None,
                       forward_sink: Optional[List[int]] = None,
                       span_parent=None, slot: str = "primary"
                       ) -> Tuple[int, bytes, Dict[str, str]]:
        """One routing pass over the rotation (failover loop); ``tried``
        seeds replicas to skip (the hedge path excludes the primary's),
        ``record`` collects the indices this pass touches.  A terminal
        replica answer normally counts in ``forwarded_total``; with
        ``forward_sink`` set it is appended there instead, so the hedged
        caller (racing two passes) counts once per client request.

        Tracing: each pass gets its own ``proxy.pass`` span (so the
        primary and its hedge carry DISTINCT span ids under one trace),
        and each forward attempt inside a pass gets a ``proxy.forward``
        span whose context is stamped onto the ``X-DKS-Trace`` header the
        replica sees — a retried/failed-over request's replica spans
        parent to the exact attempt that reached them."""

        tr = self._tracer
        pass_span = (tr.begin("proxy.pass", parent=span_parent, slot=slot)
                     if tr.enabled else None)
        result: Tuple[int, bytes, Dict[str, str]] = (500, b"", {})
        try:
            result = self._route_explain_pass(
                method, body, headers, klass, tried, record, forward_sink,
                pass_span, slot)
            return result
        finally:
            if pass_span is not None:
                tr.end(pass_span, status=result[0])

    def _route_explain_pass(self, method, body, headers, klass, tried,
                            record, forward_sink, pass_span, slot
                            ) -> Tuple[int, bytes, Dict[str, str]]:
        tr = self._tracer
        tried = set() if tried is None else tried
        last_503: Optional[Tuple[int, bytes]] = None
        last_429: Optional[Tuple[bytes, float]] = None
        while True:
            replica = self._pick(tried)
            if replica is None:
                if last_429 is not None:
                    # every live replica reported saturation: shed at the
                    # proxy with the replicas' own backoff hint instead of
                    # queueing on a fleet that already said no
                    payload, retry_s = last_429
                    self._m_sheds.inc()
                    self._flight.record("shed", component="proxy",
                                        reason="replicas_saturated",
                                        klass=klass)
                    return 429, payload, {
                        "Retry-After": str(max(1, int(math.ceil(retry_s))))}
                if last_503 is not None:
                    # every live replica self-declared unserviceable: the
                    # most informative answer is a replica's own 503 body
                    return last_503[0], last_503[1], {}
                return 503, json.dumps({
                    "error": "no live replicas",
                    "replicas": {r.address: r.alive
                                 for r in self.replicas}}).encode(), {}
            tried.add(replica.index)
            if record is not None:
                record.append(replica.index)
            backoff = replica.saturated_for(klass)
            if time.monotonic() < backoff:
                # recently answered 429 for this class: skip without
                # forwarding — early shedding costs the proxy nothing and
                # keeps the saturated replica's handler threads free for
                # queued work
                if last_429 is None:
                    last_429 = (json.dumps({
                        "error": f"replica {replica.address} saturated",
                        "reason": "replicas_saturated"}).encode(),
                        backoff - time.monotonic())
                continue
            fwd_headers = headers
            fspan = None
            if tr.enabled:
                fspan = tr.begin(
                    "proxy.forward",
                    parent=pass_span.context if pass_span is not None
                    else None,
                    replica=replica.index, address=replica.address,
                    slot=slot)
                # the replica parents its server.request span to THIS
                # forward attempt, not to whatever the client minted
                fwd_headers = {k: v for k, v in (headers or {}).items()
                               if k.lower() != _tracing.TRACE_HEADER.lower()}
                fwd_headers[_tracing.TRACE_HEADER] = \
                    _tracing.format_trace_header(fspan.context)
            outcome = "unknown"
            try:
                try:
                    status, payload, resp_headers = self._forward(
                        method, "/explain", body, replica,
                        headers=fwd_headers)
                except _ConnectFailed:
                    # never reached the replica: mark dead, retry on the
                    # next — a connect failure cannot double-execute the
                    # request
                    outcome = "connect_failed"
                    logger.warning("replica %s refused connection; removed "
                                   "from rotation", replica.address)
                    replica.alive = False
                    self._m_retried_connects.inc()
                    self._flight.record("replica_dead",
                                        replica=replica.index,
                                        address=replica.address,
                                        cause="connect_failed")
                    continue
                except socket.timeout:
                    # slow, not dead: a legitimately long request (first
                    # compile of a new bucket shape runs 40-140 s through a
                    # tunnel; the worker's own first_batch_grace_s is 600 s)
                    # must not evict a healthy replica from rotation.  This
                    # client gets a 504; liveness stays governed by
                    # connection state and the /healthz prober (a truly
                    # wedged replica fails those).
                    outcome = "timeout"
                    self._replica_failed(replica)
                    self._m_replica_errors.inc()
                    logger.warning(
                        "replica %s exceeded request_timeout_s=%.0f",
                        replica.address, self.request_timeout_s)
                    return 504, json.dumps({
                        "error": f"replica {replica.address} did not answer "
                                 f"within {self.request_timeout_s:.0f}s",
                        "replica": replica.index}).encode(), {}
                except (OSError, http.client.HTTPException) as e:
                    # mid-request failure: the replica may have processed
                    # (or be processing) it — surface THIS request as that
                    # replica's error, exactly like the reference's
                    # died-with-its-actor requests; new requests route
                    # elsewhere.  HTTPException covers a replica killed
                    # after sending headers but before the body
                    # (IncompleteRead/BadStatusLine) — not an OSError
                    outcome = "mid_request_failure"
                    replica.alive = False
                    self._replica_failed(replica)
                    self._m_replica_errors.inc()
                    self._flight.record("replica_dead",
                                        replica=replica.index,
                                        address=replica.address,
                                        cause="mid_request_failure")
                    logger.warning("replica %s failed mid-request: %s",
                                   replica.address, e)
                    return 502, json.dumps({
                        "error": f"replica {replica.address} failed "
                                 f"mid-request: {e}",
                        "replica": replica.index}).encode(), {}
                outcome = str(status)
                if status == 429:
                    retry_s = self._retry_after_s(resp_headers, payload)
                    try:
                        reason = json.loads(payload).get("reason")
                    except (ValueError, AttributeError):
                        reason = None
                    if reason == "rate_limited":
                        # the replica shed THIS CLIENT, not load: the fleet
                        # has headroom, so neither mark the replica
                        # saturated (that would let one abusive client deny
                        # every client) nor retry elsewhere (each replica
                        # keys its own bucket — rotating would multiply the
                        # client's allowance xN)
                        return 429, payload, {
                            "Retry-After":
                                str(max(1, int(math.ceil(retry_s))))}
                    if reason != "projected_wait":
                        # queue_full (or unknown): a capacity signal for
                        # this priority class — mark it saturated so
                        # same-class requests skip it until the backoff
                        # elapses.  projected_wait is NOT marked: it
                        # depends on THIS request's deadline (a
                        # deadline-less request would have been admitted),
                        # so treating it as saturation would shed traffic
                        # the replica still accepts.
                        replica.saturated_until[klass] = (time.monotonic()
                                                          + retry_s)
                    # either way retry a replica with more headroom
                    # (shedding is pre-dispatch, so the retry cannot
                    # double-execute); if every replica says 429 the
                    # exhausted-rotation path above sheds at the proxy with
                    # the replicas' own backoff hint
                    last_429 = (payload, retry_s)
                    continue
                if status == 503:
                    # the replica answered but DECLINED to serve (its own
                    # watchdog declared a device wedge and fast-503s, or it
                    # is shutting down).  It refused before dispatch, so a
                    # retry cannot double-execute — demote it (the prober
                    # re-admits it when /healthz answers 200 again) and try
                    # the next replica; without this a wedged-but-alive
                    # worker would permanently fail its share of the
                    # traffic.
                    replica.alive = False
                    self._replica_failed(replica)
                    # its OWN counter: an operator must be able to tell
                    # alive-but-wedged (device-level, this one) from
                    # crashing-at-connect (process-level) — the two call
                    # for opposite remediations
                    self._m_503_demotions.inc()
                    self._flight.record("replica_dead",
                                        replica=replica.index,
                                        address=replica.address,
                                        cause="503_demotion")
                    logger.warning("replica %s answered 503 (self-declared "
                                   "unserviceable); removed from rotation",
                                   replica.address)
                    last_503 = (status, payload)
                    continue
                if forward_sink is not None:
                    forward_sink.append(replica.index)
                else:
                    self._m_forwarded.inc()
                # propagate the replica's Content-Type: a binary wire
                # response must reach the client labelled as such (the
                # proxy forwards bodies verbatim, both directions)
                ctype = next((v for k, v in resp_headers.items()
                              if k.lower() == "content-type"), None)
                return status, payload, (
                    {"Content-Type": ctype} if ctype else {})
            finally:
                if fspan is not None:
                    tr.end(fspan, outcome=outcome)

    # ------------------------------------------------------------------ #

    def _probe_loop(self):
        """Return recovered replicas to rotation (dead → /healthz → live).

        The prober is also the autoscaler's readiness oracle: it tracks
        the warmup ladder's distinct ``{"status": "warming"}`` 503 (so
        ``dks_autoscale_replicas{state="warming"}`` is honest), admits a
        freshly added replica the moment its ladder finishes, and marks
        standbys ``warm_ready`` WITHOUT admitting them — activation stays
        a scaler decision.  Retired replicas are never probed."""

        contprof().register_current_thread("tick")
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._probe_sweep()
            except Exception:
                # the prober is the process's ONE dead-replica recovery
                # path: an unexpected raise (beyond the per-probe
                # OSError/HTTPException handling below) must cost one
                # sweep, never the thread (DKS-C005)
                logger.exception("prober sweep failed; retrying next "
                                 "interval")

    def _probe_sweep(self) -> None:
        """One pass over the roster (see :meth:`_probe_loop`)."""

        for r in list(self.replicas):
            if self._stop.is_set():
                break
            if r.retired or (r.alive and not r.standby):
                continue
            try:
                # short dedicated timeout: a wedged-but-accepting
                # replica must not stall the prober for the full
                # request timeout and starve other replicas' recovery
                status, body, _ = self._forward("GET", "/healthz", b"",
                                                r, timeout_s=5.0)
            except (OSError, http.client.HTTPException):
                # HTTPException too: a garbage health response must not
                # kill the prober thread (that would silently disable
                # dead-replica recovery for the process lifetime)
                r.warm_ready = False
                continue
            if status == 200:
                r.warming = False
                if r.standby:
                    # ready but deliberately held out of rotation: the
                    # scaler's activate_standby() is the admission
                    if not r.warm_ready:
                        r.warm_ready = True
                        logger.info("standby replica %s warm and "
                                    "ready for activation", r.address)
                    continue
                logger.info("replica %s recovered; back in rotation",
                            r.address)
                r.warm_ready = True
                r.alive = True
                self._flight.record("replica_recovered",
                                    replica=r.index, address=r.address)
            else:
                r.warm_ready = False
                try:
                    r.warming = (json.loads(body).get("status")
                                 == "warming")
                except (ValueError, AttributeError):
                    r.warming = False

    def _render_metrics(self) -> str:
        # rendered SOLELY by the shared registry (declarations live in
        # __init__; the catalog in docs/OBSERVABILITY.md)
        return self.metrics.render()

    def attach_supervisor(self, supervisor) -> None:
        """Let ``/statusz`` show the replica supervisor's restart stats
        next to the liveness it already tracks (``ReplicaManager`` calls
        this once the supervisor is up)."""

        self._supervisor = supervisor

    def attach_autoscaler(self, autoscaler) -> None:
        """Let ``/statusz`` render the autoscaler panel (fleet target,
        bounds, last decision, cooldowns) next to the replica rotation it
        acts on (``serving/autoscaler.Autoscaler`` calls this once)."""

        self._autoscaler = autoscaler

    def _statusz_detail(self) -> Dict:
        """Proxy-specific ``/statusz`` block: replica liveness (the
        rotation's own view), lifecycle states, saturation backoffs,
        supervisor restart stats and the autoscaler panel when attached."""

        now = time.monotonic()
        replicas = []
        for r in self.replicas:
            backoff = r.saturated_any()
            replicas.append({
                "index": r.index, "address": r.address,
                "alive": bool(r.alive),
                "state": r.state(),
                # remaining backoff, counting DOWN to readmission (0 =
                # not saturated) — named for what it measures
                "saturation_expires_in_s": round(max(0.0, backoff - now),
                                                 2),
            })
        sup = self._supervisor
        scaler = self._autoscaler
        return {
            "replicas": replicas,
            "live_replicas": sum(1 for r in self.replicas if r.alive),
            "replica_states": self.replica_state_counts(),
            "hedging": self.hedge_policy is not None,
            "supervisor": sup.stats() if sup is not None else None,
            "autoscaler": (scaler.statusz_panel()
                           if scaler is not None else None),
        }

    def _make_handler(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, payload: bytes,
                       ctype: str = "application/json",
                       headers: Optional[Dict[str, str]] = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(payload)

            def _handle(self):
                path_only, _, query = self.path.partition("?")
                route = path_only.rstrip("/")
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                if route == "/statusz":
                    ctype, page = statusz_response(
                        proxy.health, query, detail=proxy._statusz_detail())
                    self._reply(200, page.encode(), ctype=ctype)
                    return
                if route == "/healthz":
                    live = [r.address for r in proxy.replicas if r.alive]
                    code = 200 if live else 503
                    self._reply(code, json.dumps({
                        "status": "ok" if live else "no live replicas",
                        "live": live,
                        "dead": [r.address for r in proxy.replicas
                                 if not (r.alive or r.retired
                                         or r.standby)],
                        "draining": [r.address for r in proxy.replicas
                                     if r.draining],
                        "standby": [r.address for r in proxy.replicas
                                    if r.standby]}).encode())
                    return
                if route == "/metrics":
                    # a real parameter match, not a substring scan:
                    # ?federate=10 or ?unfederate=1 must NOT trigger an
                    # N-replica scrape sweep
                    federate = urllib.parse.parse_qs(
                        query or "").get("federate", [])
                    if federate and federate[-1] == "1":
                        # the federated page: every replica's exposition
                        # merged under a replica label (fleet view)
                        self._reply(200, proxy.federated_metrics().encode(),
                                    ctype="text/plain; version=0.0.4")
                        return
                    self._reply(200, proxy._render_metrics().encode(),
                                ctype="text/plain; version=0.0.4")
                    return
                if route == "/fleetz":
                    # the interpreted per-tenant cost rollup (JSON;
                    # schema in docs/OBSERVABILITY.md)
                    self._reply(200, json.dumps(proxy.fleet_rollup(),
                                                default=repr).encode())
                    return
                if route == "/debugz":
                    payload = proxy._flight.to_payload()
                    # trace exemplars from the proxy's own latency
                    # histogram (replica exemplars ride /fleetz)
                    payload["exemplars"] = proxy.metrics.exemplars()
                    self._reply(200, json.dumps(payload).encode())
                    return
                if route == "/profilez":
                    params = urllib.parse.parse_qs(query or "")
                    federate = params.get("federate", [])
                    if federate and federate[-1] == "1":
                        # fleet flamegraph: every replica's collapsed
                        # stacks merged (counts sum) over the scrape pool
                        self._reply(200,
                                    proxy.federated_profile().encode(),
                                    ctype="text/plain; charset=utf-8")
                        return
                    ctype, page = contprof().profilez_payload(params)
                    self._reply(200, page, ctype=ctype)
                    return
                if route == "/qualityz":
                    params = urllib.parse.parse_qs(query or "")
                    federate = params.get("federate", [])
                    if federate and federate[-1] == "1":
                        # fleet correctness view: per-replica quality
                        # documents folded over the scrape pool
                        self._reply(200,
                                    proxy.federated_quality().encode())
                        return
                    # the proxy audits nothing itself — the non-federated
                    # answer is the empty schema document
                    self._reply(200,
                                json.dumps(quality_stub_doc()).encode())
                    return
                if route != "/explain":
                    self._reply(404, json.dumps(
                        {"error": "unknown route"}).encode())
                    return
                # forward the client's scheduling headers so the replica's
                # scheduler/admission/cache see the declared SLO and key —
                # plus the wire-negotiation pair (Content-Type/Accept), so
                # binary bodies forward VERBATIM instead of being
                # re-encoded (the replica answers the negotiation; the
                # proxy stays format-agnostic)
                sched_headers = {k: v for k, v in self.headers.items()
                                 if k.lower().startswith("x-dks-")}
                for wire_header in ("Content-Type", "Accept"):
                    value = self.headers.get(wire_header)
                    if value:
                        sched_headers[wire_header] = value
                if not proxy.trust_client_header:
                    # the replica would otherwise see every request from
                    # the proxy's address (one shared bucket) — and a
                    # client-chosen key would defeat rate limiting
                    # entirely (fresh key = fresh full bucket), so the
                    # proxy stamps the peer address unless an
                    # authenticated edge is declared trusted
                    sched_headers = {k: v for k, v in sched_headers.items()
                                     if k.lower() != "x-dks-client"}
                    sched_headers["X-DKS-Client"] = self.client_address[0]
                elif not any(k.lower() == "x-dks-client"
                             for k in sched_headers):
                    sched_headers["X-DKS-Client"] = self.client_address[0]
                code, payload, extra = proxy.handle_explain(
                    self.command, body, headers=sched_headers)
                # the replica's own Content-Type (binary wire vs JSON)
                # rides in `extra` — lift it out so _reply doesn't emit a
                # duplicate header
                ctype = extra.pop("Content-Type", "application/json")
                self._reply(code, payload, ctype=ctype, headers=extra)

            do_GET = _handle
            do_POST = _handle

            def log_message(self, fmt, *args):
                logger.debug("fan-in http: " + fmt, *args)

        return Handler

    def start(self) -> "FanInProxy":
        contprof().acquire()
        self._prof_released = False
        self._httpd = _ProxyHTTPServer((self.host, self.port),
                                       self._make_handler())
        self.port = self._httpd.server_address[1]
        t_http = threading.Thread(target=self._httpd.serve_forever,
                                  daemon=True)
        t_probe = threading.Thread(target=self._probe_loop, daemon=True)
        t_http.start()
        t_probe.start()
        self.health.start()
        self._threads = [t_http, t_probe]
        logger.info("fan-in proxy on %s:%d over %d replicas",
                    self.host, self.port, len(self.replicas))
        return self

    def stop(self):
        self._stop.set()
        # one-shot: a double stop() must not release another holder's
        # profiler reference
        if not getattr(self, "_prof_released", True):
            self._prof_released = True
            contprof().release()
        self.health.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._hedge_pool is not None:
            # wait=False: a pass stuck in a transport timeout must not
            # stall shutdown; its thread is bounded by those timeouts
            self._hedge_pool.shutdown(wait=False)
        pool = getattr(self, "_fleet_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)  # scrapes are timeout-bounded too

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class _ConnectFailed(OSError):
    def __init__(self, replica: _Replica):
        super().__init__(f"connect to {replica.address} failed")
        self.replica = replica


# --------------------------------------------------------------------- #


class _PodProcess:
    """``Popen``-shaped aggregate of one multi-host pod's member
    processes — the unit the manager/supervisor/prober reason about.

    A pod is one SPMD mesh: losing ANY member wedges the others' next
    collective (no Python-level timeout can recover a blocked gloo/XLA
    collective), so a dead member means a dead pod.  :meth:`poll`
    encodes that: the first observed member exit SIGKILLs the survivors
    (SIGTERM would be ignored — followers defer to the shutdown
    broadcast that will never come) and reports the pod dead with the
    first corpse's returncode, which is exactly what makes the existing
    :class:`~distributedkernelshap_tpu.resilience.supervisor.
    ReplicaSupervisor` restart whole pods with no pod-specific code.
    Deliberate shutdown goes through :meth:`terminate`: the lead's
    SIGTERM handler runs the drain handshake and releases the followers
    via the shutdown broadcast (followers ignore SIGTERM by design)."""

    def __init__(self, members: List[subprocess.Popen]):
        if not members:
            raise ValueError("a pod needs at least one member process")
        self.members = list(members)
        self.returncode: Optional[int] = None
        self.pid = self.members[0].pid  # lead's pid, for logs

    def poll(self) -> Optional[int]:
        codes = [m.poll() for m in self.members]
        if self.returncode is not None:
            return self.returncode
        dead = [c for c in codes if c is not None]
        if not dead:
            return None
        for m, c in zip(self.members, codes):
            if c is None:
                m.kill()
        self.returncode = dead[0]
        return self.returncode

    def terminate(self) -> None:
        for m in self.members:
            if m.poll() is None:
                m.terminate()

    def kill(self) -> None:
        for m in self.members:
            if m.poll() is None:
                m.kill()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for m in self.members:
            left = (None if deadline is None
                    else max(0.05, deadline - time.monotonic()))
            m.wait(timeout=left)  # TimeoutExpired propagates, like Popen
        if self.returncode is None:
            self.returncode = self.members[0].returncode
        return self.returncode


class ReplicaManager:
    """Spawn + supervise N replica units — single-device worker processes
    (``replica_worker.py``) or, with ``pod_processes > 1``, whole
    multi-host PODS (``serving/main.py --coordinator``: one lead serving
    HTTP + followers joining each device call via the broadcast
    protocol) — and their fan-in proxy.  A pod is one fleet citizen: the
    prober keys health off the lead's ``/healthz``, the supervisor
    restarts the whole pod when any member dies, the autoscaler scales
    in pod increments, and warm-standby pods pre-warm through the
    broadcast warmup ladder like any replica.

    The in-process analog of the reference's Ray autorestart
    (``cluster/ray_cluster.yaml:63``): an exited worker is relaunched by a
    :class:`~distributedkernelshap_tpu.resilience.supervisor.
    ReplicaSupervisor` (crash-loop exponential backoff + jitter, dead
    replicas marked straight out of the proxy's rotation), re-probed, and
    returned to rotation by the proxy's own health prober.

    ``restart_policy`` tunes the backoff; ``hedge_policy`` enables
    tail-latency hedging at the fan-in (``resilience/hedging.py``)."""

    def __init__(self, n_replicas: int,
                 factory: str = "distributedkernelshap_tpu.serving."
                                "replica_worker:adult_factory",
                 host: str = "127.0.0.1",
                 max_batch_size: int = 10,
                 pipeline_depth: Optional[int] = None,
                 pin_devices: bool = True,
                 restart: bool = True,
                 env_extra: Optional[Dict[str, str]] = None,
                 startup_timeout_s: float = 300.0,
                 restart_policy: Optional[RestartPolicy] = None,
                 hedge_policy: Optional[HedgePolicy] = None,
                 autoscale=None,
                 pod_processes: int = 1):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if pod_processes < 1:
            raise ValueError("pod_processes must be >= 1")
        self.n_replicas = n_replicas
        #: processes per replica UNIT.  1 (default) spawns plain
        #: single-device ``replica_worker`` processes; >1 spawns each
        #: replica as a multi-host POD — ``serving/main.py --coordinator``
        #: members over a local coordinator, aggregated behind one
        #: ``_PodProcess`` so the proxy/supervisor/autoscaler stay
        #: pod-oblivious.  The autoscaler reads this attribute to accrue
        #: replica-seconds in process units (pods cost P x per second).
        self.pod_processes = pod_processes
        self.factory = factory
        self.host = host
        self.max_batch_size = max_batch_size
        self.pipeline_depth = pipeline_depth
        self.pin_devices = pin_devices
        self.restart = restart
        self.restart_policy = restart_policy
        self.hedge_policy = hedge_policy
        #: elastic fleet sizing: ``None``/falsy (the default — the
        #: ``autoscale=off`` escape hatch for pinned/single-replica
        #: deployments) serves the fixed ``n_replicas`` forever; an
        #: ``AutoscalerConfig`` (``serving/autoscaler.py``) starts a
        #: scaler over this manager's spawn/retire hooks.  Requires
        #: ``restart=True`` (retirement rides on the supervisor).
        self.autoscale = autoscale or None
        if self.autoscale is not None and not restart:
            raise ValueError("autoscale needs restart=True (scale-down "
                             "retires replicas through the supervisor)")
        self.autoscaler = None
        self.env_extra = dict(env_extra or {})
        self.startup_timeout_s = startup_timeout_s
        self.ports: List[int] = []
        self.procs: List[subprocess.Popen] = []
        self.proxy: Optional[FanInProxy] = None
        self._stop = threading.Event()
        # serialises restart-vs-shutdown: without it a worker exiting just
        # as stop() runs can be respawned AFTER stop() already swept the
        # proc list, leaking a server process (and its chip) past shutdown
        self._procs_lock = threading.Lock()
        self.supervisor: Optional[ReplicaSupervisor] = None

    # ------------------------------------------------------------------ #

    def _reserve_ports(self, n: Optional[int] = None) -> List[int]:
        """OS-assigned free ports, reserved briefly then released to the
        workers.  The tiny bind race this leaves is acceptable for a
        single-host deployment (k8s mode gives each replica its own pod)."""

        import socket

        socks, ports = [], []
        for _ in range(self.n_replicas if n is None else n):
            s = socket.socket()
            s.bind((self.host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    def _spawn(self, index: int) -> subprocess.Popen:
        if self.pod_processes > 1:
            return self._spawn_pod(index)
        env = dict(os.environ, **self.env_extra)
        # always stamped (not only under pin_devices): the fault harness
        # filters replica=K specs on it, and logs/metrics want it too
        env["DKS_REPLICA_INDEX"] = str(index)
        if self.pin_devices:
            # one chip per worker on TPU hosts; harmless elsewhere.  The
            # worker re-checks this before importing jax.
            env["TPU_VISIBLE_CHIPS"] = str(index)
        argv = [sys.executable, "-m",
                "distributedkernelshap_tpu.serving.replica_worker",
                "--factory", self.factory,
                "--host", self.host,
                "--port", str(self.ports[index]),
                "--max_batch_size", str(self.max_batch_size)]
        if self.pipeline_depth:
            argv += ["--pipeline_depth", str(self.pipeline_depth)]
        logger.info("spawning replica %d on port %d", index,
                    self.ports[index])
        return subprocess.Popen(argv, env=env)

    def _spawn_pod(self, index: int) -> _PodProcess:
        """One replica unit as a multi-host pod: ``pod_processes`` members
        of ``serving/main.py --coordinator`` over a locally reserved
        coordinator port.  The lead serves HTTP on the unit's probed port
        (``self.ports[index]`` — the proxy/prober/supervisor see exactly
        the surface a plain worker exposes); followers get their own
        reserved ports for the liveness-only follower health listener.
        Ports are reserved FRESH per spawn: a restarted pod must
        rendezvous on its own coordinator, never a half-dead
        predecessor's."""

        P = self.pod_processes
        cport, *follower_ports = self._reserve_ports(P)
        members = []
        for k in range(P):
            env = dict(os.environ, **self.env_extra)
            env["DKS_REPLICA_INDEX"] = str(index)
            if self.pin_devices:
                # contiguous chip blocks per pod: member k of pod i owns
                # chip i*P + k, so pods never share a device
                env["TPU_VISIBLE_CHIPS"] = str(index * P + k)
            argv = [sys.executable, "-m",
                    "distributedkernelshap_tpu.serving.main",
                    "--coordinator", f"127.0.0.1:{cport}",
                    "--num_processes", str(P),
                    "--process_id", str(k),
                    "--factory", self.factory,
                    "--host", self.host,
                    "--port", str(self.ports[index] if k == 0
                                  else follower_ports[k - 1]),
                    "--max_batch_size", str(self.max_batch_size)]
            if self.pipeline_depth:
                argv += ["--pipeline_depth", str(self.pipeline_depth)]
            members.append(subprocess.Popen(argv, env=env))
        logger.info("spawning pod %d (%d processes, lead on port %d, "
                    "coordinator 127.0.0.1:%d)", index, P,
                    self.ports[index], cport)
        return _PodProcess(members)

    def _wait_healthy(self, index: int, timeout_s: float):
        """``True`` (ready), ``False`` (dead/unreachable) or ``"warming"``
        — the replica answers /healthz with the warmup ladder's distinct
        503 ``{"status": "warming"}``.  Warming is startup PROGRESS, not
        failure: the manager must neither kill the process (the
        crash-loop the warmup readiness gate exists to prevent) nor fail
        startup over it — the proxy's prober readmits the replica the
        moment its ladder finishes and /healthz answers 200."""

        deadline = time.monotonic() + timeout_s
        warming = False
        while time.monotonic() < deadline and not self._stop.is_set():
            if self.procs[index].poll() is not None:
                return False  # died during startup
            try:
                conn = http.client.HTTPConnection(self.host,
                                                  self.ports[index],
                                                  timeout=5)
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                status, body = resp.status, resp.read()
                conn.close()
                if status == 200:
                    return True
                try:
                    warming = json.loads(body).get("status") == "warming"
                except ValueError:
                    warming = False
            except OSError:
                pass
            time.sleep(0.5)
        return "warming" if warming else False

    # -- elastic fleet hooks (serving/autoscaler.py) -------------------- #

    def spawn_replica(self, standby: bool = False) -> Optional[int]:
        """Scale-up: spawn ONE new worker on a fresh port and register it
        with the proxy (out of rotation until its warmup ladder finishes
        and the prober admits it — the ``warming`` pre-warm state).  The
        worker inherits the fleet's env, so ``DKS_WARMUP`` defaults the
        ladder ON exactly like construction-time workers.  A previously
        retired slot is reused (same index at proxy and supervisor —
        ``track`` clears the retirement) so scale cycles don't grow the
        roster.  Returns the replica index, or ``None`` if the manager
        is stopping."""

        with self._procs_lock:
            if self._stop.is_set():
                return None
            reused = next(
                (i for i in range(len(self.procs))
                 if self.supervisor is not None
                 and self.supervisor.is_retired(i)), None)
            if reused is not None:
                index = reused
                self.ports[index] = self._reserve_ports(1)[0]
                self.procs[index] = self._spawn(index)
            else:
                index = len(self.procs)
                self.ports.append(self._reserve_ports(1)[0])
                self.procs.append(self._spawn(index))
        if self.supervisor is not None:
            self.supervisor.track(index)
        self.proxy.add_target(self.host, self.ports[index], standby=standby,
                              index=reused)
        return index

    def retire_replica(self, index: int, grace_s: float = 10.0) -> None:
        """Scale-down's second half (the scaler calls this AFTER the
        drain emptied the replica's queues): mark the worker retired with
        the supervisor (its exit is on purpose — no restart), SIGTERM it
        (the worker's signal handler runs ``server.stop()``, which
        answers any straggler with a retriable pre-dispatch 503), and
        retire its slot at the proxy."""

        if self.supervisor is not None:
            self.supervisor.retire(index)
        with self._procs_lock:
            proc = self.procs[index]
            if proc is not None and proc.poll() is None:
                proc.terminate()
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass  # D-state child: the shutdown sweep retries
        self.proxy.finish_drain(index)

    # ------------------------------------------------------------------ #

    def start(self, proxy_port: int = 0,
              proxy_host: Optional[str] = None) -> "ReplicaManager":
        self.ports = self._reserve_ports()
        self.procs = [self._spawn(i) for i in range(self.n_replicas)]
        # probe startup health CONCURRENTLY: one dead replica must delay
        # serving by at most one startup_timeout_s, not one per dead chip
        ok = [False] * self.n_replicas

        def _probe(i):
            ok[i] = self._wait_healthy(i, self.startup_timeout_s)

        probers = [threading.Thread(target=_probe, args=(i,), daemon=True)
                   for i in range(self.n_replicas)]
        for t in probers:
            t.start()
        for t in probers:
            t.join()
        # a replica still compiling its warmup ladder counts as STARTED
        # (its process is up and making progress) but not yet routable —
        # killing the fleet because every replica is warming would be the
        # crash-loop the readiness gate exists to prevent
        if not any(ok):
            self.stop()
            raise RuntimeError(
                f"no replica became healthy within "
                f"{self.startup_timeout_s:.0f}s (factory={self.factory})")
        if not all(o is True for o in ok):
            logger.warning(
                "replicas %s not ready at startup (%s still warming); "
                "serving with %d/%d — the prober readmits warmers when "
                "their ladder finishes",
                [i for i, o in enumerate(ok) if o is not True],
                [i for i, o in enumerate(ok) if o == "warming"],
                sum(o is True for o in ok), self.n_replicas)
        self.proxy = FanInProxy(
            [(self.host, p) for p in self.ports],
            host=proxy_host or self.host, port=proxy_port,
            hedge_policy=self.hedge_policy).start()
        for i, o in enumerate(ok):
            if o is not True:
                self.proxy.replicas[i].alive = False
        if self.restart:
            self.supervisor = ReplicaSupervisor(
                self.procs, self._spawn, proxy=self.proxy,
                policy=self.restart_policy,
                lock=self._procs_lock).start()
            # restart stats join the proxy's /statusz replica block
            self.proxy.attach_supervisor(self.supervisor)
        if self.autoscale is not None:
            # imported here: autoscaler.py is fleet-agnostic (it drives
            # this manager OR any object with the spawn/retire hooks),
            # so module-level imports stay acyclic
            from distributedkernelshap_tpu.serving.autoscaler import (
                Autoscaler,
            )

            self.autoscaler = Autoscaler(self, self.proxy,
                                         config=self.autoscale)
            # baseline the capacity projection at the starting fleet
            # size, so the first scale event rescales from a known
            # denominator instead of waiting a gather tick
            self.autoscaler.capacity_hint(max(1, self.n_replicas))
            self.autoscaler.start()
        return self

    def stop(self):
        self._stop.set()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.proxy is not None:
            self.proxy.stop()
        with self._procs_lock:  # no respawn may interleave with the sweep
            for proc in self.procs:
                if proc.poll() is None:
                    proc.terminate()
            deadline = time.monotonic() + 10
            for proc in self.procs:
                try:
                    proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    try:
                        # reap: an unreaped kill leaves a zombie and stale
                        # poll() bookkeeping for the manager's lifetime
                        proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        pass  # D-state child: nothing more we can do

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
