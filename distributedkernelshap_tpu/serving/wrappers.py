"""Serving model wrappers.

Counterparts of the reference's Ray Serve backends
(``explainers/wrappers.py:10-88``): ``KernelShapModel`` builds and fits a
``KernelShap`` from ``(predictor, background_data, constructor_kwargs,
fit_kwargs)`` and explains one JSON request at a time; ``BatchKernelShapModel``
accepts a coalesced list of requests.

The key TPU-native difference: the reference explains batched requests
*sequentially inside a replica* (``wrappers.py:81-88`` — and its Analysis
notebook observes request batching "brings no benefit"), whereas here a
request batch becomes ONE device call over the stacked instances, so
server-side batching actually multiplies throughput.
"""

import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from distributedkernelshap_tpu.kernel_shap import KernelShap
from distributedkernelshap_tpu.serving import wire

logger = logging.getLogger(__name__)

#: env opt-out for the exact-path auto-selection (default ON): a served
#: lifted tree ensemble with raw-margin outputs answers every request with
#: closed-form exact Shapley values instead of the sampled estimator
EXACT_AUTO_ENV = "DKS_EXACT_AUTO"

#: env opt-out for the DeepSHAP auto-selection specifically (default ON;
#: the global EXACT_AUTO_ENV also applies): a served lifted neural graph
#: answers every request with backprop attribution instead of the
#: sampled estimator
DEEPSHAP_AUTO_ENV = "DKS_DEEPSHAP_AUTO"

# per-request explain-path accounting, process-global so the serving
# registry can render it via a callback (same pattern as the compile
# accountant): {'exact': n, 'sampled': n} requests answered per path
_path_lock = threading.Lock()
_path_counts: Dict[str, float] = {"exact": 0.0, "exact_tn": 0.0,
                                  "deepshap": 0.0, "sampled": 0.0}


def record_explain_path(path: str, n: int = 1) -> None:
    with _path_lock:
        _path_counts[path] = _path_counts.get(path, 0.0) + n


def explain_path_counts() -> Dict[tuple, float]:
    """``{(path,): count}`` — the registry-callback shape."""

    with _path_lock:
        return {(p,): n for p, n in _path_counts.items()}


def attach_path_metrics(registry) -> None:
    """Register ``dks_serve_explain_path_total{path}`` on ``registry``:
    requests answered per evaluation path (exact closed-form TreeSHAP vs
    the sampled KernelSHAP estimator), fed by the serving wrappers."""

    registry.counter(
        "dks_serve_explain_path_total",
        "Request slots explained by evaluation path (exact = closed-form "
        "interventional TreeSHAP, exact_tn = exact tensor-network "
        "contraction, deepshap = DeepSHAP multiplier backprop for lifted "
        "neural graphs, sampled = KernelSHAP estimator); includes "
        "warmup-ladder rungs, which drive the same entry points.",
        labelnames=("path",)).set_function(explain_path_counts)

# explain options a deployment may pin for every request: the keys every
# request path supports — including the pipelined get_explanation_async,
# whose signature has no **kwargs ('silent' would additionally collide
# with the hard-coded silent=True of the serving calls)
_EXPLAIN_KWARG_KEYS = ("nsamples", "l1_reg", "interactions")


def _check_explain_kwargs(explain_kwargs) -> Dict[str, Any]:
    kwargs = dict(explain_kwargs or {})
    bad = sorted(set(kwargs) - set(_EXPLAIN_KWARG_KEYS))
    if bad:
        raise ValueError(
            f"explain_kwargs supports only {_EXPLAIN_KWARG_KEYS} (the keys "
            f"every serving request path accepts); got {bad}")
    if kwargs.get("interactions") and kwargs.get("nsamples") != "exact":
        # value-level coupling checked here so a misconfigured deployment
        # fails at construction, not on every request
        raise ValueError(
            "explain_kwargs={'interactions': True} requires "
            "'nsamples': 'exact' (closed-form interventional TreeSHAP)")
    return kwargs


def _request_array(request) -> np.ndarray:
    """Extract the instance array from a request: either an object with a
    ``.json`` attribute/dict (flask-style parity) or a plain dict."""

    payload = getattr(request, "json", request)
    if callable(payload):  # some frameworks expose .json() as a method
        payload = payload()
    return np.atleast_2d(np.asarray(payload["array"], dtype=np.float32))


class KernelShapModel:
    """Builds + fits a KernelShap explainer and serves single requests
    (reference ``wrappers.py:10-59``)."""

    def __init__(self,
                 predictor,
                 background_data: np.ndarray,
                 constructor_kwargs: Dict[str, Any],
                 fit_kwargs: Dict[str, Any],
                 explain_kwargs: Optional[Dict[str, Any]] = None):
        if hasattr(predictor, "predict_proba"):
            predict_fcn = predictor.predict_proba
        elif hasattr(predictor, "predict"):
            logger.warning("Predictor does not have predict_proba attribute, "
                           "defaulting to predict")
            predict_fcn = predictor.predict
        else:
            predict_fcn = predictor  # already a callable / framework predictor
        self.explainer = KernelShap(predict_fcn, **constructor_kwargs)
        self.explainer.fit(background_data, **fit_kwargs)
        # per-deployment explain options applied to every request, e.g.
        # {'nsamples': 'exact'} for a served tree regressor or a fixed
        # nsamples/l1_reg policy; validated at construction so a bad key
        # fails the deployment, not every request
        self.explain_kwargs = _check_explain_kwargs(explain_kwargs)
        self._resolve_explain_path()

    @classmethod
    def from_explainer(cls, explainer: KernelShap,
                       explain_kwargs: Optional[Dict[str, Any]] = None
                       ) -> "KernelShapModel":
        """Wrap an already-fitted explainer (e.g. one restored with
        ``KernelShap.load``) without refitting."""

        model = cls.__new__(cls)
        model.explainer = explainer
        model.explain_kwargs = _check_explain_kwargs(explain_kwargs)
        model._resolve_explain_path()
        return model

    def _serving_engine(self):
        """The fitted engine behind this deployment's explainer (the
        DistributedExplainer wraps the real engine one level down)."""

        from distributedkernelshap_tpu.registry.classify import (
            serving_engine,
        )

        return serving_engine(self)

    def _resolve_explain_path(self) -> None:
        """Auto-select ``nsamples='exact'`` for deployments whose fitted
        predictor admits an analytic (sampling-free) path: lifted tree
        ensembles with raw-margin outputs (lgbm/xgb/sklearn-tree lifts —
        the packed TreeSHAP route), tensor-train-structured predictors
        (``models/tensor_net.py`` — the DP contraction route) and lifted
        neural graphs (``attribution/deepshap.py`` — the DeepSHAP
        backprop route), all at identity link.  The analytic paths beat
        the sampled estimator on both wall-clock and determinism there,
        so they are the default.  A pinned ``nsamples`` key always wins
        (including ``nsamples=None`` as an explicit opt-out), as does
        ``DKS_EXACT_AUTO=0`` (all paths) and ``DKS_DEEPSHAP_AUTO=0``
        (the backprop path only).  Sets ``explain_path`` (``'exact'`` |
        ``'exact_tn'`` | ``'deepshap'`` | ``'sampled'``) and
        ``explain_path_reason`` for the per-request span/metric
        attribution.  A TT predictor or neural graph that fails a
        readiness gate stays sampled with the reason counted in
        ``dks_tensor_shap_fallback_total`` /
        ``dks_deepshap_fallback_total``."""

        from distributedkernelshap_tpu.utils import resolve_bool_env

        engine = self._serving_engine()
        if "nsamples" in self.explain_kwargs:
            if self.explain_kwargs["nsamples"] == "exact":
                flavor = (getattr(engine, "_exact_flavor", lambda: None)()
                          if engine is not None else None)
                path = {"tn": "exact_tn",
                        "deepshap": "deepshap"}.get(flavor, "exact")
            else:
                path = "sampled"
            self.explain_path, self.explain_path_reason = path, "pinned"
            return
        self.explain_path, self.explain_path_reason = "sampled", "default"
        if not resolve_bool_env(EXACT_AUTO_ENV, True):
            self.explain_path_reason = "auto_disabled"
            return
        try:
            # the ONE path classifier (registry/classify.py — factored
            # out of this method when the multi-tenant registry landed,
            # so ingest-time classification and serving auto-selection
            # can never disagree)
            from distributedkernelshap_tpu.registry.classify import (
                classify_path,
            )

            if engine is None:
                return
            decision = classify_path(self)
            if decision.path == "exact_tree":
                self.explain_kwargs["nsamples"] = "exact"
                self.explain_path = "exact"
                self.explain_path_reason = "auto"
                logger.info(
                    "serving auto-selected the exact TreeSHAP path for a "
                    "lifted %s (set %s=0 or pin nsamples to opt out)",
                    type(engine.predictor).__name__, EXACT_AUTO_ENV)
            elif decision.path == "exact_tn":
                self.explain_kwargs["nsamples"] = "exact"
                self.explain_path = "exact_tn"
                self.explain_path_reason = "auto"
                logger.info(
                    "serving auto-selected the exact tensor-network "
                    "path for a %s (set %s=0 or pin nsamples to opt "
                    "out)", type(engine.predictor).__name__,
                    EXACT_AUTO_ENV)
            elif decision.path == "deepshap":
                from distributedkernelshap_tpu.attribution.deepshap import (
                    record_deepshap_fallback,
                )

                if not resolve_bool_env(DEEPSHAP_AUTO_ENV, True):
                    # its own opt-out on top of the global one, and an
                    # operational fact worth a counter either way
                    self.explain_path_reason = "auto_disabled"
                    record_deepshap_fallback("auto_disabled")
                else:
                    self.explain_kwargs["nsamples"] = "exact"
                    self.explain_path = "deepshap"
                    self.explain_path_reason = "auto"
                    logger.info(
                        "serving auto-selected the DeepSHAP backprop "
                        "path for a %s (set %s=0 or %s=0 or pin "
                        "nsamples to opt out)",
                        type(engine.predictor).__name__,
                        DEEPSHAP_AUTO_ENV, EXACT_AUTO_ENV)
            elif decision.tn_fallback is not None:
                # a TN-structured deployment staying sampled is an
                # operational fact worth a counter, not a mystery
                from distributedkernelshap_tpu.ops.tensor_shap import (
                    record_tn_fallback,
                )

                record_tn_fallback(decision.tn_fallback)
            elif decision.deepshap_fallback is not None:
                # same accounting for graph-bearing deployments
                from distributedkernelshap_tpu.attribution.deepshap import (
                    record_deepshap_fallback,
                )

                record_deepshap_fallback(decision.deepshap_fallback)
        except Exception:  # never fail a deployment over path selection
            logger.debug("exact-path auto-selection probe failed",
                         exc_info=True)

    def reset(self) -> None:
        """Drop device-resident state (uploaded constants, jitted
        executables) so the next explain rebuilds from host copies.

        Called by the serving watchdog after a device wedge: buffers that
        lived on a backend that has since restarted are dead handles, and
        feeding them to a fresh backend fails opaquely.  Everything dropped
        here is a cache — correctness is unaffected, the next call just
        pays re-upload + re-trace."""

        inner = getattr(self.explainer, "_explainer", None)
        reset = getattr(inner, "reset_device_state", None)
        if reset is not None:
            reset()

    def __call__(self, request) -> str:
        """Explain a single request; returns the Explanation as JSON
        (the wire schema of ``interface.Explanation.to_json``)."""

        instance = _request_array(request)
        explanation = self.explainer.explain(instance, silent=True,
                                             **self.explain_kwargs)
        record_explain_path(self.explain_path, 1)
        return explanation.to_json()

    #: the server checks this capability flag before asking for per-request
    #: wire formats — swapped-in stub models (benchmarks, tests) without it
    #: keep the historical JSON-only contract
    supports_wire_formats = True

    #: per-row reduction scope: each request's phi depends only on its own
    #: rows plus X-independent constants — no engine path reduces across
    #: request rows — so content-identical tenants may share one padded
    #: device call bit-identically (cross-tenant continuous batching;
    #: ``registry/classify.share_eligible`` gates on this declaration, so
    #: stub models without it are never coalesced across tenants)
    per_row_reduction = True

    def _resplit_payloads(self, instances: np.ndarray, shap_values,
                          expected_value, raw_predictions: np.ndarray,
                          split_sizes: List[int],
                          interaction_values=None, formats=None) -> List:
        """Re-split one batched run into per-request payloads, reusing the
        batched raw outputs (no per-slice predictor pass).

        ``formats[i]`` selects slot ``i``'s encoding: ``'json'`` (default —
        the historical Explanation JSON string) or ``'binary'`` (the wire
        format's raw-bytes explanation, ``serving/wire.py``).  Binary slots
        skip ``build_explanation`` + ``to_json`` entirely — that per-request
        document build is the single largest host cost on the serving hot
        path, which is exactly what the streaming protocol exists to kill.
        """

        sv = shap_values if isinstance(shap_values, list) else [shap_values]
        e_val = list(np.atleast_1d(np.asarray(expected_value)))
        payloads = []
        offset = 0
        for slot, size in enumerate(split_sizes):
            sl = slice(offset, offset + size)
            fmt = formats[slot] if formats is not None else "json"
            if fmt == "binary":
                payloads.append(wire.encode_explanation(
                    [values[sl] for values in sv], e_val,
                    raw_predictions[sl],
                    interaction_values=None if interaction_values is None
                    else [v[sl] for v in interaction_values]))
                offset += size
                continue
            piece = self.explainer.build_explanation(
                instances[sl],
                [values[sl] for values in sv],
                e_val,
                raw_predictions=raw_predictions[sl],
            )
            if interaction_values is not None:
                piece.data['raw']['interaction_values'] = [
                    v[sl] for v in interaction_values]
            payloads.append(piece.to_json())
            offset += size
        return payloads

    def stage_rows(self, instances: np.ndarray):
        """Pre-upload a stacked request batch to the device (the serving
        staging pipeline's hook): returns an engine ``StagedRows`` whose
        H2D copy is already in flight, or ``None`` when this deployment's
        explain path cannot consume pre-staged rows (host-eval,
        interactions, active l1 — the sync-fallback paths; exact tree
        deployments stage like sampled ones since the exact path rides
        the donated-entry hot path).  The returned object is accepted by
        :meth:`explain_batch_async` in place of the raw array."""

        engine = self.explainer._explainer
        stage = getattr(engine, "stage_rows", None)
        if stage is None:
            return None
        return stage(instances, **self.explain_kwargs)

    def explain_batch(self, instances: np.ndarray,
                      split_sizes: Optional[List[int]] = None,
                      formats: Optional[List[str]] = None) -> List:
        """Explain a stacked array in one device call and re-split the
        results into per-request payloads (JSON strings, or wire bytes for
        slots marked ``'binary'`` in ``formats``)."""

        explanation = self.explainer.explain(instances, silent=True,
                                             **self.explain_kwargs)
        if split_sizes is None:
            split_sizes = [1] * instances.shape[0]
        record_explain_path(self.explain_path, len(split_sizes))
        return self._resplit_payloads(
            instances, explanation.shap_values, explanation.expected_value,
            explanation.data["raw"]["raw_prediction"], split_sizes,
            interaction_values=explanation.data["raw"].get(
                "interaction_values"), formats=formats)

    def explain_batch_async(self, instances,
                            split_sizes: Optional[List[int]] = None,
                            formats: Optional[List[str]] = None):
        """Pipelined variant of :meth:`explain_batch`: dispatches the device
        work immediately and returns ``finalize() -> List[payload]``.

        The server's dispatcher thread calls this back-to-back for successive
        request batches while finalizer threads fetch + postprocess earlier
        ones, overlapping the per-call D2H round trips that dominate
        small-batch latency on a tunnelled TPU.  ``instances`` may be an
        engine ``StagedRows`` from :meth:`stage_rows` — its device buffer is
        then consumed directly (no second H2D), and the host copy feeds the
        JSON re-split."""

        engine = self.explainer._explainer
        # both explainer kinds expose the same async contract:
        # KernelExplainerEngine directly, DistributedExplainer since round 4
        # (true pipelining on single-process meshes — the serving pod shape —
        # where the sharded fetch has no collectives; multi-host falls back
        # to a synchronous closure internally)
        fin = engine.get_explanation_async(instances, **self.explain_kwargs)
        host_rows = getattr(instances, "host", instances)
        sizes = ([1] * host_rows.shape[0] if split_sizes is None
                 else list(split_sizes))
        record_explain_path(self.explain_path, len(sizes))

        def finalize() -> List:
            values, info = fin()
            return self._resplit_payloads(
                host_rows, values, info["expected_value"],
                info["raw_prediction"], sizes,
                interaction_values=info.get("interaction_values"),
                formats=formats)

        return finalize

    # ---- anytime refinement (progressive rounds, ISSUE 16) ----------- #

    @property
    def supports_anytime(self) -> bool:
        """Whether this deployment can answer a request progressively
        (``X-DKS-Error-Budget`` / streamed rounds).  Only the sampled
        estimator refines: exact paths are already exact, interactions
        and active l1 ride the sync fallback, host-eval cannot carry
        device state across rounds.  The engine itself rejects budgets
        whose coalition space enumerates exactly."""

        if self.explain_path != "sampled":
            return False
        if self.explain_kwargs.get("interactions"):
            return False
        engine = getattr(self.explainer, "_explainer", None)
        if engine is None or not hasattr(engine, "anytime_supported"):
            return False
        nsamples = self.explain_kwargs.get("nsamples")
        try:
            # mirror the engine's explain-time default ('auto'), not the
            # kwarg's absence: the deployment's effective l1 behaviour is
            # what the anytime path would silently diverge from
            if engine._l1_active(self.explain_kwargs.get("l1_reg", "auto"),
                                 nsamples):
                return False
            return bool(engine.anytime_supported(nsamples))
        except Exception:  # never fail admission over eligibility probing
            logger.debug("anytime eligibility probe failed", exc_info=True)
            return False

    def anytime_begin(self, instances: np.ndarray):
        """Start a refinement run for one request's rows; returns the
        engine's ``AnytimeRun`` handle (step it between scheduler turns)
        or ``None`` when this request cannot refine after all."""

        engine = self.explainer._explainer
        return engine.anytime_begin(
            np.atleast_2d(np.asarray(instances, dtype=np.float32)),
            nsamples=self.explain_kwargs.get("nsamples"))

    def anytime_payload(self, instances: np.ndarray, result,
                        fmt: str = "json"):
        """Final per-request payload from a round result — same encodings
        as :meth:`_resplit_payloads` (one slot), so an anytime answer is
        wire-identical to a single-shot one.  Records the request against
        the sampled path (one request, however many rounds it took)."""

        from distributedkernelshap_tpu.ops.explain import split_shap_values

        engine = self.explainer._explainer
        sv = split_shap_values(result.phi, engine.vector_out)
        record_explain_path(self.explain_path, 1)
        return self._resplit_payloads(
            np.atleast_2d(np.asarray(instances, dtype=np.float32)),
            sv, result.expected_value, result.raw_prediction,
            [result.phi.shape[0]], formats=[fmt])[0]

    def anytime_frame(self, result, final: bool = False) -> bytes:
        """One stream frame (``serving/wire.py`` DKSS envelope) for a
        round result."""

        from distributedkernelshap_tpu.ops.explain import split_shap_values

        engine = self.explainer._explainer
        sv = split_shap_values(result.phi, engine.vector_out)
        if not isinstance(sv, list):
            sv = [sv]
        if final:
            # the final frame answers the request — path accounting's
            # one-per-request increment (anytime_payload does the same
            # for non-streamed anytime answers)
            record_explain_path(self.explain_path, 1)
        return wire.encode_round_frame(
            sv, result.expected_value, result.raw_prediction,
            result.round_index, result.est_err, final=final)

    def anytime_rounds(self) -> int:
        """Rounds in this deployment's refinement schedule (0 = cannot
        refine) — the warmup ladder's ``rounds=<k>`` signature suffix."""

        engine = getattr(self.explainer, "_explainer", None)
        if engine is None or not hasattr(engine, "_anytime_schedule"):
            return 0
        schedule = engine._anytime_schedule(
            self.explain_kwargs.get("nsamples"))
        return 0 if schedule is None else schedule.n_rounds

    def anytime_warm(self, batch_sizes, rounds: Optional[int] = None):
        """Compile the per-round entries for the warmup ladder's batch
        rungs: runs a zero-instance refinement to completion (or
        ``rounds`` rounds) per batch size so serving traffic never pays
        the round traces.  Returns the number of rounds compiled."""

        engine = self.explainer._explainer
        schedule = engine._anytime_schedule(
            self.explain_kwargs.get("nsamples"))
        if schedule is None:
            return 0
        compiled = 0
        for b in batch_sizes:
            run = self.anytime_begin(
                np.zeros((int(b), engine.M), dtype=np.float32))
            if run is None:
                continue
            limit = schedule.n_rounds if rounds is None \
                else min(int(rounds), schedule.n_rounds)
            for _ in range(limit):
                run.step()
                compiled += 1
        return compiled


class BatchKernelShapModel(KernelShapModel):
    """Explains a coalesced list of requests (reference ``wrappers.py:62-88``)
    — but as ONE stacked device call instead of a sequential per-request
    loop."""

    def __call__(self, requests: List) -> List[str]:  # type: ignore[override]
        arrays = [_request_array(r) for r in requests]
        sizes = [a.shape[0] for a in arrays]
        stacked = np.concatenate(arrays, axis=0)
        return self.explain_batch(stacked, split_sizes=sizes)
