"""Client-side request fan-out.

The reference fans out one HTTP request per instance as Ray remote tasks
(``benchmarks/serve_explanations.py:96-139``: ``distribute_request.remote``
doing ``requests.get(url, json={'array': ...})``).  Here the fan-out is a
thread pool — requests are IO-bound HTTP calls, the server coalesces them
into device batches.

Each worker thread keeps one persistent HTTP/1.1 connection (the server
speaks keep-alive): without reuse, every request costs a TCP handshake and
spawns a fresh handler thread server-side, and on a single-core host that
thread churn starves the GIL the explain pipeline needs.
"""

import http.client
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence
from urllib.parse import urlparse

import numpy as np

_tls = threading.local()


def _get_connection(scheme: str, netloc: str,
                    timeout: float) -> http.client.HTTPConnection:
    conns = getattr(_tls, "conns", None)
    if conns is None:
        conns = _tls.conns = {}
    key = (scheme, netloc)
    conn = conns.get(key)
    if conn is None:
        cls = (http.client.HTTPSConnection if scheme == "https"
               else http.client.HTTPConnection)
        conn = conns[key] = cls(netloc, timeout=timeout)
    elif conn.timeout != timeout:
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
    return conn


def _drop_connection(scheme: str, netloc: str) -> None:
    conn = getattr(_tls, "conns", {}).pop((scheme, netloc), None)
    if conn is not None:
        conn.close()


def explain_request(url: str, instance: np.ndarray, timeout: float = 300.0) -> str:
    """POST one instance (or minibatch) to the explanation endpoint and
    return the JSON payload, reusing this thread's connection."""

    parsed = urlparse(url)
    path = parsed.path or "/"
    body = json.dumps({"array": np.asarray(instance).tolist()}).encode()
    headers = {"Content-Type": "application/json"}
    for attempt in (0, 1):  # one retry through a fresh connection
        conn = _get_connection(parsed.scheme or "http", parsed.netloc, timeout)
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read().decode()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}: {payload}")
            return payload
        except TimeoutError:
            # a timed-out request may still be queued server-side; re-sending
            # it would duplicate work on an already-overloaded server
            _drop_connection(parsed.scheme or "http", parsed.netloc)
            raise
        except (http.client.HTTPException, ConnectionError, OSError):
            _drop_connection(parsed.scheme or "http", parsed.netloc)
            if attempt:
                raise
    raise AssertionError("unreachable")


def distribute_requests(url: str,
                        data: np.ndarray,
                        batch_mode: str = "ray",
                        minibatches: Optional[Sequence[np.ndarray]] = None,
                        max_workers: int = 16,
                        timeout: float = 300.0) -> List[str]:
    """Fan requests out to the endpoint.

    ``batch_mode='ray'`` mirrors the reference's server-side batching mode
    (one single-row request per instance, ``k8s_serve_explanations.py:181``);
    ``'default'`` sends client-side minibatches (``:184``), either supplied
    via ``minibatches`` or one row each.

    ``max_workers`` bounds the in-flight requests; the default is sized for a
    colocated single-core client, where more threads only fight the serving
    pipeline for the GIL.
    """

    if batch_mode == "ray" or minibatches is None:
        parts = np.split(data, data.shape[0])
    else:
        parts = list(minibatches)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = [pool.submit(explain_request, url, p, timeout) for p in parts]
        return [f.result() for f in futures]
