"""Client-side request fan-out.

The reference fans out one HTTP request per instance as Ray remote tasks
(``benchmarks/serve_explanations.py:96-139``: ``distribute_request.remote``
doing ``requests.get(url, json={'array': ...})``).  Here the fan-out is a
thread pool — requests are IO-bound HTTP calls, the server coalesces them
into device batches.
"""

import json
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as np


def explain_request(url: str, instance: np.ndarray, timeout: float = 300.0) -> str:
    """POST one instance (or minibatch) to the explanation endpoint and
    return the JSON payload."""

    body = json.dumps({"array": np.asarray(instance).tolist()}).encode()
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def distribute_requests(url: str,
                        data: np.ndarray,
                        batch_mode: str = "ray",
                        minibatches: Optional[Sequence[np.ndarray]] = None,
                        max_workers: int = 64,
                        timeout: float = 300.0) -> List[str]:
    """Fan requests out to the endpoint.

    ``batch_mode='ray'`` mirrors the reference's server-side batching mode
    (one single-row request per instance, ``k8s_serve_explanations.py:181``);
    ``'default'`` sends client-side minibatches (``:184``), either supplied
    via ``minibatches`` or one row each.
    """

    if batch_mode == "ray" or minibatches is None:
        parts = np.split(data, data.shape[0])
    else:
        parts = list(minibatches)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = [pool.submit(explain_request, url, p, timeout) for p in parts]
        return [f.result() for f in futures]
