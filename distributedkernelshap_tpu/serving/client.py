"""Client-side request fan-out.

The reference fans out one HTTP request per instance as Ray remote tasks
(``benchmarks/serve_explanations.py:96-139``: ``distribute_request.remote``
doing ``requests.get(url, json={'array': ...})``).  Here the fan-out is a
thread pool — requests are IO-bound HTTP calls, the server coalesces them
into device batches.

Each worker thread keeps one persistent HTTP/1.1 connection (the server
speaks keep-alive): without reuse, every request costs a TCP handshake and
spawns a fresh handler thread server-side, and on a single-core host that
thread churn starves the GIL the explain pipeline needs.
"""

import http.client
import json
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence
from urllib.parse import urlparse

import numpy as np

import distributedkernelshap_tpu.observability.tracing as _tracing
import distributedkernelshap_tpu.serving.wire as _wire

_tls = threading.local()

# per-host negotiated transport ("binary" | "json"), learned from the
# server's responses: a 415 (or a 400 answered to a binary body — the
# pre-wire servers' reaction, they JSON-parse everything) downgrades the
# host to JSON for the process lifetime, so one failed probe per host is
# the whole negotiation cost.  Shared across threads (benign to race: the
# value converges and every transition is also handled per-request).
_negotiated: dict = {}
_negotiated_lock = threading.Lock()


def reset_negotiation_cache() -> None:
    """Forget learned per-host transports (tests; or after a fleet
    upgrade, to let clients re-probe binary)."""

    with _negotiated_lock:
        _negotiated.clear()

#: ceiling on any single backoff sleep, whatever the server's hint says —
#: a buggy/adversarial ``Retry-After: 86400`` must not park a client thread
#: for a day
MAX_BACKOFF_S = 30.0

#: base for the exponential backoff used when the server gave no hint
#: (connection failures, 502/503 without Retry-After)
BASE_BACKOFF_S = 0.25


def _get_connection(scheme: str, netloc: str,
                    timeout: float) -> http.client.HTTPConnection:
    conns = getattr(_tls, "conns", None)
    if conns is None:
        conns = _tls.conns = {}
    key = (scheme, netloc)
    conn = conns.get(key)
    if conn is None:
        cls = (http.client.HTTPSConnection if scheme == "https"
               else http.client.HTTPConnection)
        conn = conns[key] = cls(netloc, timeout=timeout)
    elif conn.timeout != timeout:
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
    return conn


def _drop_connection(scheme: str, netloc: str) -> None:
    conn = getattr(_tls, "conns", {}).pop((scheme, netloc), None)
    if conn is not None:
        conn.close()


def parse_retry_after(headers, payload) -> Optional[float]:
    """A 429's backoff hint: ``Retry-After`` header, else ``retry_after_s``
    in the JSON body; ``None`` when absent or garbled.  The ONE parser of
    this wire hint — the fan-in proxy layers its own floor/default on
    top (``FanInProxy._retry_after_s``)."""

    value = headers.get("Retry-After") if headers else None
    if value is not None:
        try:
            return max(0.0, float(value))
        except ValueError:
            pass
    try:
        return max(0.0, float(json.loads(payload)["retry_after_s"]))
    except (ValueError, KeyError, TypeError):
        return None


def _request_body(instance: np.ndarray, binary: bool,
                  extra_headers: Optional[dict], stream: bool = False):
    """(body, headers) for one transport: binary wire framing (raw float32
    row bytes + binary Accept) or the historical JSON document.

    ``stream`` prepends the round-stream content type to the Accept list.
    A pre-anytime server ignores the unknown entry and matches whatever
    else the list offers (plain wire, or nothing -> JSON) — streaming
    negotiation rides the SAME request, no extra probe."""

    if binary:
        body = _wire.encode_request(instance)
        headers = {"Content-Type": _wire.CONTENT_TYPE,
                   "Accept": _wire.CONTENT_TYPE}
    else:
        body = json.dumps({"array": np.asarray(instance).tolist()}).encode()
        headers = {"Content-Type": "application/json"}
    if stream:
        headers["Accept"] = (_wire.STREAM_CONTENT_TYPE
                             + (", " + headers["Accept"]
                                if "Accept" in headers else ""))
    headers.update(extra_headers or {})
    return body, headers


def _read_exact(resp, n: int) -> bytes:
    """Read exactly ``n`` bytes from a response (http.client de-chunks);
    a short read means the server tore the stream mid-frame."""

    chunks = []
    got = 0
    while got < n:
        piece = resp.read(n - got)
        if not piece:
            raise _wire.WireError(
                f"stream torn mid-frame: wanted {n} bytes, got {got}")
        chunks.append(piece)
        got += len(piece)
    return b"".join(chunks)


def _read_stream(resp, on_partial: Optional[Callable]) -> dict:
    """Consume a round-frame stream incrementally: each frame is decoded
    the moment its bytes arrive (header first, then exactly the declared
    payload — partial results reach ``on_partial`` without buffering the
    whole response), and the final frame's structured dict is returned.
    Raises :class:`wire.WireError`/:class:`wire.WireVersionError` on torn
    frames or unknown stream versions — a half-written frame can never
    surface as phi."""

    while True:
        header = _read_exact(resp, _wire.STREAM_HEADER_SIZE)
        length = _wire.stream_frame_length(header)
        payload = _read_exact(resp, length) if length else b""
        frame, _ = _wire.decode_round_frame(header + payload)
        if frame["final"]:
            if resp.read():  # drain the chunked terminator for keep-alive
                raise _wire.WireError("stream carries bytes past the "
                                      "final frame")
            return frame
        if on_partial is not None:
            on_partial(frame)


def explain_request(url: str, instance: np.ndarray, timeout: float = 300.0,
                    max_retries: int = 4,
                    extra_headers: Optional[dict] = None,
                    wire_format: str = "json",
                    stream: bool = False,
                    on_partial: Optional[Callable[[dict], None]] = None,
                    _sleep: Callable[[float], None] = time.sleep,
                    _rng: Optional[random.Random] = None):
    """POST one instance (or minibatch) to the explanation endpoint and
    return the payload, reusing this thread's connection.

    ``wire_format`` selects the transport and the return type:

    * ``'json'`` (default, the historical contract) — JSON request body,
      returns the raw Explanation JSON payload ``str``.
    * ``'binary'`` / ``'auto'`` — the zero-copy wire protocol
      (``serving/wire.py``): binary request body + binary ``Accept``;
      returns a dict ``{'shap_values': [K x (B, M)], 'expected_value',
      'raw_prediction'}`` whatever transport the negotiation lands on.
      A server answering 415 (a future-version decoder) **or** 400 to the
      binary body (a pre-wire server JSON-parsing everything) downgrades
      this host to JSON for the process (``reset_negotiation_cache`` to
      re-probe); the downgraded request is re-sent as JSON on the same
      connection without consuming the retry budget, and the structured
      dict is then extracted from the JSON document — callers never see
      the transport.

    ``stream=True`` asks for progressive refinement (anytime serving):
    the round-stream content type is prepended to the Accept list, and
    against a stream-capable server each partial round frame is decoded
    the moment it arrives and handed to ``on_partial`` (a dict with
    ``shap_values``/``expected_value``/``raw_prediction``/``round``/
    ``converged``/``est_err``), in round order; the call returns the
    FINAL frame's dict.  Against a pre-anytime server or proxy the
    unknown Accept entry is ignored and the response degrades to one
    ordinary answer (plain wire or JSON, whatever the rest of the list
    negotiates): ``on_partial`` is never called and the single answer is
    returned as the same structured dict — so ``stream=True`` always
    returns a dict, whatever ``wire_format`` says, and works unchanged
    against every server generation.  A stream torn mid-frame (or
    carrying an unknown stream version) never surfaces partial phi: the
    connection is dropped and the request retried within the ordinary
    budget (``on_partial`` may then see early rounds again — partials
    are idempotent refinements, replaying them is harmless).

    Retriable failures are retried within a bounded budget
    (``max_retries`` beyond the first attempt), with capped, jittered
    backoff:

    * **429** — the server's explicit backpressure.  The ``Retry-After``
      hint is HONOURED (capped at :data:`MAX_BACKOFF_S`, with up to 25%
      added jitter so a shed burst doesn't resynchronise into a retry
      stampede at exactly hint seconds).
    * **502 / 503** — a crashed-mid-request or self-declared-unserviceable
      replica behind a fan-in.  Explanations are deterministic and
      content-addressed, so re-sending is idempotent: a duplicate
      execution produces a bit-identical payload (and on a cache-enabled
      server costs no second device call).  Exponential backoff from
      :data:`BASE_BACKOFF_S`.
    * **connection failures** — retried through a fresh connection (the
      request may never have been sent).
    * **undecodable payloads** — a response body that is not valid UTF-8
      was corrupted on the wire; a re-fetch is idempotent and returns the
      clean (bit-identical) answer.

    NOT retried: timeouts (the request may still be queued server-side —
    re-sending duplicates load on an already-struggling server; the 504
    status a proxy synthesises for a slow replica is equally terminal
    here), and any other HTTP error (4xx/500 are answers, not outages).
    ``_sleep``/``_rng`` are test seams.

    Tracing (``DKS_TRACE=1``): the client MINTS the trace id — one
    ``client.request`` root span per call, one ``client.attempt`` child
    span per wire attempt (retries get distinct span ids), and the
    attempt's context rides the ``X-DKS-Trace`` header so proxy and
    replica spans downstream share the trace id.
    """

    parsed = urlparse(url)
    path = parsed.path or "/"
    if wire_format not in ("json", "binary", "auto"):
        raise ValueError(f"wire_format must be 'json', 'binary' or 'auto', "
                         f"got {wire_format!r}")
    host_key = (parsed.scheme or "http", parsed.netloc)
    with _negotiated_lock:
        negotiated = _negotiated.get(host_key)
    # binary unless this host already downgraded; plain 'json' never probes
    sent_binary = wire_format != "json" and negotiated != "json"
    body, headers = _request_body(instance, sent_binary, extra_headers,
                                  stream=stream)
    rng = _rng or random.Random()
    tr = _tracing.tracer()
    root = None
    if tr.enabled:
        # an explicit X-DKS-Trace in extra_headers adopts the caller's
        # trace (batch drivers stamping one trace across a fan-out)
        root = tr.begin("client.request",
                        parent=_tracing.parse_trace_header(
                            _tracing.header_get(headers)),
                        rows=int(np.asarray(instance).reshape(
                            -1, np.asarray(instance).shape[-1]).shape[0]))
    attempt = 0
    last_status = None
    tentative_400 = False
    try:
        while True:
            conn = _get_connection(parsed.scheme or "http", parsed.netloc,
                                   timeout)
            backoff = None
            aspan = None
            if root is not None:
                aspan = tr.begin("client.attempt", parent=root.context,
                                 attempt=attempt)
                headers = {k: v for k, v in headers.items()
                           if k.lower() != _tracing.TRACE_HEADER.lower()}
                headers[_tracing.TRACE_HEADER] = \
                    _tracing.format_trace_header(aspan.context)
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                ctype = (resp.headers.get("Content-Type")
                         or "").split(";", 1)[0].strip().lower()
                if stream and resp.status == 200 \
                        and ctype == _wire.STREAM_CONTENT_TYPE:
                    last_status = resp.status
                    try:
                        frame = _read_stream(resp, on_partial)
                        tr.end(aspan, status=resp.status,
                               rounds=frame["round"] + 1)
                        return frame
                    except (_wire.WireError, ValueError) as e:
                        # a torn/garbled stream never surfaces partial
                        # phi: drop the (desynced) connection and
                        # re-fetch — rounds are deterministic, so a
                        # replayed stream is bit-identical
                        tr.end(aspan, outcome="stream_torn")
                        _drop_connection(parsed.scheme or "http",
                                         parsed.netloc)
                        if attempt >= max_retries:
                            raise RuntimeError(
                                f"HTTP 200: torn round-frame stream "
                                f"({e})") from e
                        backoff = BASE_BACKOFF_S * (2.0 ** attempt)
                        attempt += 1
                        _sleep(min(MAX_BACKOFF_S,
                                   backoff * (1.0 + 0.25 * rng.random())))
                        continue
                raw = resp.read()
                last_status = resp.status
                tr.end(aspan, status=resp.status)
                if sent_binary and resp.status in (415, 400):
                    # the server does not speak the wire format — 415 is
                    # the explicit signal (version mismatch), 400 the
                    # pre-wire servers' reaction (they JSON-parse every
                    # body).  Downgrade the host and re-send as JSON on
                    # the SAME connection; negotiation is not a failure,
                    # so the retry budget is untouched.  sent_binary is
                    # now False, so a second 415/400 is terminal.  A 400
                    # is only a TENTATIVE verdict: a wire-capable server
                    # also answers 400 for a bad SLO header, and caching
                    # 'json' off that would silently disable the binary
                    # transport for every later request to the host — so
                    # the cached verdict is withdrawn below if the JSON
                    # re-send draws the same 400 (the request itself was
                    # bad, not the transport).
                    tentative_400 = resp.status == 400
                    with _negotiated_lock:
                        _negotiated[host_key] = "json"
                    sent_binary = False
                    body, headers = _request_body(instance, False,
                                                  extra_headers,
                                                  stream=stream)
                    continue
                if tentative_400 and resp.status == 400:
                    # the JSON re-send failed identically: the 400 was
                    # about THIS request, not the wire format — forget
                    # the downgrade so the host keeps its binary path
                    with _negotiated_lock:
                        if _negotiated.get(host_key) == "json":
                            del _negotiated[host_key]
                resp_binary = _wire.is_wire_content_type(
                    resp.headers.get("Content-Type"))
                if resp_binary:
                    payload = raw  # framing validated at decode below
                else:
                    try:
                        payload = raw.decode()
                    except UnicodeDecodeError:
                        # corrupted on the wire (bit-rot, an injected
                        # garble): idempotency makes a re-fetch safe, so
                        # spend a retry on a clean copy instead of
                        # surfacing garbage — but only for statuses that
                        # are retriable anyway; a garbled 400/500 is still
                        # an answer the server would deterministically
                        # repeat
                        if resp.status not in (200, 429, 502, 503) \
                                or attempt >= max_retries:
                            raise RuntimeError(
                                f"HTTP {resp.status}: undecodable (corrupt) "
                                f"payload of {len(raw)} bytes")
                        payload = None
                        backoff = BASE_BACKOFF_S * (2.0 ** attempt)
                if payload is not None:
                    if resp.status == 200:
                        if wire_format == "json" and not stream:
                            return payload
                        try:
                            return (_wire.decode_explanation(payload)
                                    if resp_binary else
                                    _wire.explanation_payload_from_json(
                                        payload))
                        except (_wire.WireError, ValueError, KeyError):
                            # structured-mode analog of the undecodable
                            # branch: a torn/garbled 200 body re-fetches
                            # bit-identically
                            if attempt >= max_retries:
                                raise RuntimeError(
                                    f"HTTP 200: unparseable explanation "
                                    f"payload of {len(raw)} bytes")
                            backoff = BASE_BACKOFF_S * (2.0 ** attempt)
                    elif resp.status == 429:
                        hint = parse_retry_after(resp.headers, payload)
                        backoff = hint if hint is not None else \
                            BASE_BACKOFF_S * (2.0 ** attempt)
                    elif resp.status in (502, 503):
                        backoff = BASE_BACKOFF_S * (2.0 ** attempt)
                    if backoff is None or attempt >= max_retries:
                        raise RuntimeError(f"HTTP {resp.status}: {payload}")
            except TimeoutError:
                # a timed-out request may still be queued server-side;
                # re-sending it would duplicate work on an
                # already-overloaded server
                tr.end(aspan, outcome="timeout")
                _drop_connection(parsed.scheme or "http", parsed.netloc)
                raise
            except (http.client.HTTPException, ConnectionError, OSError):
                tr.end(aspan, outcome="connection_failed")
                _drop_connection(parsed.scheme or "http", parsed.netloc)
                if attempt >= max_retries:
                    raise
                backoff = BASE_BACKOFF_S * (2.0 ** attempt)
            attempt += 1
            # jitter INSIDE the cap: MAX_BACKOFF_S is a hard ceiling
            _sleep(min(MAX_BACKOFF_S,
                       backoff * (1.0 + 0.25 * rng.random())))
    finally:
        if root is not None:
            tr.end(root, attempts=attempt + 1, status=last_status)


def distribute_requests(url: str,
                        data: np.ndarray,
                        batch_mode: str = "ray",
                        minibatches: Optional[Sequence[np.ndarray]] = None,
                        max_workers: int = 16,
                        timeout: float = 300.0,
                        wire_format: str = "json") -> List:
    """Fan requests out to the endpoint.

    ``batch_mode='ray'`` mirrors the reference's server-side batching mode
    (one single-row request per instance, ``k8s_serve_explanations.py:181``);
    ``'default'`` sends client-side minibatches (``:184``), either supplied
    via ``minibatches`` or one row each.

    ``max_workers`` bounds the in-flight requests; the default is sized for a
    colocated single-core client, where more threads only fight the serving
    pipeline for the GIL.

    ``wire_format`` is forwarded to :func:`explain_request` — ``'json'``
    (default) returns payload strings, ``'binary'``/``'auto'`` structured
    dicts over the negotiated zero-copy transport.
    """

    if batch_mode == "ray" or minibatches is None:
        parts = np.split(data, data.shape[0])
    else:
        parts = list(minibatches)

    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = [pool.submit(explain_request, url, p, timeout,
                               wire_format=wire_format) for p in parts]
        return [f.result() for f in futures]
