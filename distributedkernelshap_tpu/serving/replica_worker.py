"""One replica process: a single-device :class:`ExplainerServer` built
from a ``module:function`` factory.

Spawned by :class:`~distributedkernelshap_tpu.serving.replicas.ReplicaManager`
(one per chip; ``TPU_VISIBLE_CHIPS`` pins the device before jax imports) or
run standalone:

    python -m distributedkernelshap_tpu.serving.replica_worker \
        --factory distributedkernelshap_tpu.serving.replica_worker:adult_factory \
        --port 8001

A factory returns ``(predictor, background_data, constructor_kwargs,
fit_kwargs)`` — the reference's Ray Serve backend constructor tuple
(``explainers/wrappers.py:10-37``), same shape ``serve_explainer`` takes.
"""

import argparse
import importlib
import logging
import signal
import threading


def adult_factory():
    """The default Adult deployment (same tuple as ``serving/main.py``)."""

    from distributedkernelshap_tpu.utils import (
        data_provenance,
        load_data,
        load_model,
    )

    data = load_data()
    predictor = load_model()
    group_names, groups = data["all"]["group_names"], data["all"]["groups"]
    return (predictor, data["background"]["X"]["preprocessed"],
            {"link": "logit", "feature_names": group_names, "seed": 0},
            {"group_names": group_names, "groups": groups,
             "data_provenance": data_provenance(data)})


def synthetic_factory():
    """A tiny deterministic logistic model on synthetic data — fast to fit,
    no dataset fetch; used by the replica tests and as a smoke deployment."""

    import numpy as np
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    clf = LogisticRegression(max_iter=200).fit(X, y)
    return (clf, X[:32], {"link": "logit", "seed": 0}, {})


def checkpoint_factory(path: str):
    """The ctor tuple behind a ``KernelShap.save`` checkpoint: rebuilds
    ``(predictor, background, ctor_kwargs, fit_kwargs)`` from the saved
    state so the model is re-fitted through the NORMAL constructor path.

    ``KernelShap.load`` + ``from_explainer`` restores the fitted engine
    directly — correct for a single process, but a multi-host pod must
    rebuild on EVERY process with ``distributed_opts`` spanning the pod's
    mesh (SPMD discipline), which only the ctor-tuple route allows.  The
    single-host ``--checkpoint`` branch keeps using ``load`` (no refit);
    pods route through here, so any checkpointed model — tree/TT/deepshap
    engine paths included — serves from a pod too."""

    import pickle

    from distributedkernelshap_tpu.data import Data

    with open(path, "rb") as f:
        state = pickle.load(f)
    bg = state["background_data"]
    fit_kwargs = {}
    if isinstance(bg, Data):
        # grouped/weighted backgrounds round-trip through fit's grouping
        # args; the raw matrix feeds the constructor path like any other
        if state.get("use_groups"):
            fit_kwargs["group_names"] = list(bg.group_names)
            fit_kwargs["groups"] = bg.groups
            weights = getattr(bg, "weights", None)
            if weights is not None:
                fit_kwargs["weights"] = weights
        bg = bg.data
    ctor_kwargs = {
        "link": state["link"],
        "feature_names": state["feature_names"],
        "categorical_names": state["categorical_names"],
        "task": state["task"],
        "seed": state["seed"],
        "engine_config": state.get("engine_config"),
    }
    provenance = (state.get("meta") or {}).get("data_provenance")
    if provenance is not None:
        fit_kwargs["data_provenance"] = provenance
    return state["predictor"], bg, ctor_kwargs, fit_kwargs


def resolve_factory(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(f"--factory must be 'module:function', got {spec!r}")
    return getattr(importlib.import_module(mod_name), fn_name)


def main():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s replica %(levelname)s %(message)s")
    parser = argparse.ArgumentParser()
    parser.add_argument("--factory", required=True,
                        help="module:function returning (predictor, "
                             "background, ctor_kwargs, fit_kwargs)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", required=True, type=int)
    parser.add_argument("--max_batch_size", default=10, type=int)
    parser.add_argument("--pipeline_depth", default=0, type=int,
                        help="0 = self-calibrate at startup")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the precompile warmup ladder (replicas "
                             "default it ON — a restarted worker otherwise "
                             "re-pays every bucket's first-jit compile on "
                             "live traffic; DKS_WARMUP=0 also disables)")
    parser.add_argument("--staging", action="store_true",
                        help="enable the double-buffered host-to-device "
                             "staging pipeline (default: resolved from "
                             "DKS_STAGING, off unless truthy)")
    args = parser.parse_args()

    factory = resolve_factory(args.factory)

    # fault injection (chaos harness): resolved from DKS_FAULTS before the
    # heavyweight imports so a bad spec fails the worker loudly at startup.
    # Specs carrying replica=K are filtered against DKS_REPLICA_INDEX, so
    # one fleet-wide env value scripts per-replica behaviour.
    from distributedkernelshap_tpu.resilience.faults import from_env

    fault_injector = from_env()

    # jax imports (inside serve_explainer's dependency chain) happen after
    # the factory resolves, with TPU_VISIBLE_CHIPS already in the
    # environment from the manager — this process initialises ONE chip.
    from distributedkernelshap_tpu.serving.server import (
        resolve_warmup_env,
        serve_explainer,
    )

    # replica workers default the warmup ladder ON (the supervisor makes
    # restarts routine, and a restarted worker must not re-pay its bucket
    # compiles on live traffic); --no-warmup or DKS_WARMUP=0 opt out, and
    # the /healthz "warming" readiness gate keeps the prober/supervisor
    # away while the ladder compiles
    warmup = False if args.no_warmup else resolve_warmup_env(default=True)

    predictor, background, ctor_kwargs, fit_kwargs = factory()
    server = serve_explainer(
        predictor, background, ctor_kwargs, fit_kwargs,
        host=args.host, port=args.port,
        max_batch_size=args.max_batch_size,
        pipeline_depth=args.pipeline_depth or None,
        fault_injector=fault_injector, warmup=warmup,
        # --staging forces it on; otherwise None defers to DKS_STAGING
        staging=True if args.staging else None)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    logging.info("replica serving on %s:%d", server.host, server.port)
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
