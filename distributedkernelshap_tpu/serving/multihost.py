"""SPMD serving over a multi-host mesh: the pod fabric.

The single-host server (``serving/server.py``) owns the whole device mesh
from one process.  On a multi-host mesh (``jax.distributed`` across
TPU-VM workers — the reference analog is Ray Serve replicas spread over the
k8s cluster, ``cluster/ray_cluster.yaml:119-141``) a device call is a
*collective* program: every process must enter the same sharded computation
in the same order, but HTTP requests arrive only at the lead process.

The bridge is a broadcast protocol, the serving-plane counterpart of the
SPMD benchmark drivers (``benchmarks/multihost_pool.py``): the lead process
runs the normal :class:`~distributedkernelshap_tpu.serving.server.ExplainerServer`
around a :class:`MultihostServingModel`, which prefixes every device call
with a broadcast frame: a ``[cmd, rows, bucket]`` header plus the batch
padded to the selected *broadcast bucket* (the warmup ladder's compile
rungs) — bytes proportional to the bucket, not the full slot, and explain
shapes still static per rung so collectives stay recompile-free.  The
default wire is the HOST-side :class:`KVStoreTransport` (the
``jax.distributed`` coordination-service KV store): frames never enter
the device queues, which matters because a device-level broadcast
schedules behind every previously dispatched async explain and would
serialize the pipelined protocol (see the class docstring).  The
device-collective wire (:class:`CollectiveTransport`) remains available;
on it every op is padded to ONE fixed MTU shape (:func:`_chunk_elems` —
a transport-level correctness requirement), so a frame costs
``1 + ceil(bucket*F/mtu)`` ops.  Follower processes sit in
:func:`follower_loop`, size the frame from the header's bucket field,
and enter the identical explain call so the mesh's collectives line up.
Responses are built on the lead only (host-side work, no collectives).
Warmup rungs broadcast as ``_CMD_WARMUP`` so every process compiles the
same signatures in lockstep before ``/healthz`` flips; shutdown is a
drain handshake (lead stops accepting, flushes in-flight dispatches,
then broadcasts the shutdown header).

Pipelining: the DEFAULT production path is the pipelined protocol —
``serve_multihost`` defaults ``distributed_opts['replicate_results']=True``
so the all-gather moves INSIDE the jitted program, fetches become local,
and :class:`PipelinedMultihostServingModel` + the follower's async
dispatch run several broadcast+explain calls in flight at the server's
pipeline depth (collective order equals dispatch order on every process
by construction), with the staging batcher forming batches one step
ahead of dispatch.  The lock-step base protocol (one device call at a
time, ``pipeline_depth`` 1) remains for explainers whose options cannot
take the async fast path, and for ``replicate_results=False`` opt-outs:
a sharded fetch embeds a ``process_allgather`` whose cross-process order
concurrent finalizes would scramble.  Within one batch the device work
is always fully sharded across all hosts' devices either way.
"""

import itertools
import logging
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.flightrec import flightrec

logger = logging.getLogger(__name__)

_CMD_SHUTDOWN = 0
_CMD_EXPLAIN = 1
_CMD_WARMUP = 2

#: broadcast header fields: ``[cmd, rows, bucket]``.  The bucket field
#: lets followers size the payload without any ladder knowledge of their
#: own — the header IS the framing contract.
_HEADER_LEN = 3


def _chunk_elems(n_features: int) -> int:
    """The wire's fixed MTU, in float32 elements.

    EVERY collective op on the wire is a float32 array of exactly this
    many elements — the header chunk (``[cmd, rows, bucket]`` zero-padded)
    and each payload chunk alike.  Shape-uniform ops are a CORRECTNESS
    requirement, not a tidiness choice: gloo (the CPU collectives
    backend) matches in-flight ops per connection pair by slot, and
    back-to-back host-level collectives of *different* byte sizes can
    cross-match under pipelining and abort the process with a preamble
    length mismatch (``op.preamble.length <= op.nbytes``).  With one MTU
    there is no op-size transition anywhere in the protocol — explain
    frames, warmup rungs and the shutdown frame are all sequences of
    identical ops, so no cross-op ordering guarantee is needed from the
    transport.  Bucketing's win becomes op COUNT: a frame carries
    ``1 + ceil(bucket*n_features/mtu)`` chunks, proportional to its
    bucket instead of the full slot."""

    return _HEADER_LEN + int(n_features)


def _payload_chunks(bucket: int, n_features: int) -> int:
    """Payload chunk count for one frame (header chunk excluded)."""

    chunk = _chunk_elems(n_features)
    return -(-(int(bucket) * int(n_features)) // chunk)


def _broadcast(value, is_source: bool):
    import jax
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(
        value, is_source=is_source if jax.process_count() > 1 else True))


class CollectiveTransport:
    """The device-collective wire: ``multihost_utils.broadcast_one_to_all``
    plus the process identity the protocol keys on.  Factored out so tier-1
    tests can drive :class:`MultihostServingModel` and :func:`follower_loop`
    with an in-process fake instead of real collectives.

    ``needs_uniform_ops`` is True: every op on this wire must be one fixed
    shape (see :func:`_chunk_elems`), so frames are MTU-chunked.  Note this
    transport also makes every broadcast a DEVICE program that queues
    behind previously dispatched async work — fine for the lock-step
    protocol, but it serializes the pipelined one, which is why
    :func:`_default_transport` prefers the host-side KV wire."""

    needs_uniform_ops = True

    @property
    def is_lead(self) -> bool:
        import jax

        return jax.process_index() == 0

    @property
    def process_index(self) -> int:
        import jax

        return jax.process_index()

    @property
    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def broadcast(self, value, is_source: bool):
        return _broadcast(value, is_source)


#: Process-local count of KV transport constructions, used to derive the
#: session key prefix WITHOUT any wire traffic: the lead constructs its
#: transport once per serve (in the model) and each follower once per
#: serve (at follower_loop entry), so the Nth construction on every
#: process belongs to the same serve session and the prefixes pair up.
_kv_session_counter = itertools.count()


class KVStoreTransport:
    """Host-side wire over the ``jax.distributed`` coordination-service
    key-value store — the default serving wire.

    The device-collective wire has a structural flaw for PIPELINED
    serving: a broadcast is itself a device program, so it schedules in
    the per-device FIFO queue BEHIND every previously dispatched async
    explain.  The wire becomes a barrier that serializes the very
    pipeline it feeds — the lead's dispatcher blocks roughly a full
    compute time per frame no matter the pipeline depth.  Frames on the
    KV store never touch the device queues (pure RPC to the coordination
    service the mesh already runs for ``jax.distributed``), so dispatch
    stays sub-millisecond regardless of device backlog, and arbitrary
    message sizes are safe — no collective op-shape matching, hence no
    MTU chunking (``needs_uniform_ops`` is False) and frame bytes exactly
    proportional to the broadcast bucket.

    Protocol: the lead publishes each op's bytes under a monotonically
    increasing sequence key; followers block on the next key in order
    (bounded-timeout gets in a retry loop — idle gaps between requests
    are normal).  Keys ``_GC_WINDOW`` ops behind the head are deleted as
    new ones are published — followers trail the lead by at most the
    pipeline depth, so the window bounds coordination-service memory
    without ever racing a reader."""

    needs_uniform_ops = False
    _GC_WINDOW = 4096

    def __init__(self):
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; the KV-store wire "
                "needs the coordination service")
        self._client = client
        self._session = f"dks/pod/wire/s{next(_kv_session_counter)}"
        self._seq = 0

    @property
    def is_lead(self) -> bool:
        import jax

        return jax.process_index() == 0

    @property
    def process_index(self) -> int:
        import jax

        return jax.process_index()

    @property
    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def broadcast(self, value, is_source: bool):
        template = np.asarray(value)
        key = f"{self._session}/{self._seq}"
        self._seq += 1
        if is_source:
            self._client.key_value_set_bytes(
                key, np.ascontiguousarray(template).tobytes())
            stale = self._seq - self._GC_WINDOW - 1
            if stale >= 0:
                try:
                    self._client.key_value_delete(
                        f"{self._session}/{stale}")
                except Exception:  # pragma: no cover - service going down
                    pass
            return template
        waits = 0
        while True:
            try:
                raw = self._client.blocking_key_value_get_bytes(key, 5000)
                break
            except Exception:
                # DEADLINE_EXCEEDED between requests is the idle-server
                # norm.  A dead coordination service also lands here, but
                # that tears the process down on its next heartbeat anyway.
                waits += 1
                if waits % 24 == 0:
                    logger.debug("follower still waiting on %s", key)
        return np.frombuffer(raw, dtype=template.dtype).reshape(
            template.shape).copy()


def _default_transport():
    """The serving wire: the host-side KV transport when the jax
    distributed client is up (always true on a real multi-process mesh),
    else the device-collective wire.  The resolution depends only on
    process-global state that is identical across the mesh, so every
    process picks the same wire."""

    try:
        return KVStoreTransport()
    except Exception:
        return CollectiveTransport()


# ---------------------------------------------------------------------- #
# Broadcast metering.  Process-global counters with a registry callback
# (the ``attach_treeshap_metrics`` pattern): the pod model is constructed
# before the server's registry exists, and the follower side has no
# registry at all, so the counts live here and the lead's server renders
# them as ``dks_pod_bcast_bytes_total{bucket}`` /
# ``dks_pod_bcast_seconds_total``.

_pod_meter_lock = lockwitness.make_lock("multihost.pod_meter")
_pod_bcast_bytes: dict = {}
_pod_bcast_seconds: float = 0.0


def record_pod_bcast(bucket: int, nbytes: int, seconds: float) -> None:
    """Count one framed broadcast (header + bucket-padded payload)."""

    global _pod_bcast_seconds
    key = str(int(bucket))
    with _pod_meter_lock:
        _pod_bcast_bytes[key] = _pod_bcast_bytes.get(key, 0.0) + float(nbytes)
        _pod_bcast_seconds += float(seconds)


def pod_bcast_byte_counts() -> dict:
    """``{(bucket,): bytes}`` — the registry-callback shape."""

    with _pod_meter_lock:
        return {(b,): n for b, n in _pod_bcast_bytes.items()}


def pod_bcast_seconds_total() -> float:
    with _pod_meter_lock:
        return _pod_bcast_seconds


def attach_pod_metrics(registry) -> None:
    """Register the ``dks_pod_*`` broadcast meters on ``registry`` as
    callback counters over the process-global accounting.  The bucket
    label space is the broadcast ladder — bounded by construction, so no
    cardinality declaration is needed."""

    registry.counter(
        "dks_pod_bcast_bytes_total",
        "Bytes broadcast lead-to-followers on the pod serving fabric "
        "(header + payload padded to the broadcast bucket), by bucket "
        "— proportional-to-bucket by construction, vs the old "
        "protocol's every-batch full slot.",
        labelnames=("bucket",)).set_function(pod_bcast_byte_counts)
    registry.counter(
        "dks_pod_bcast_seconds_total",
        "Seconds the lead's dispatcher spent inside pod broadcast "
        "sends (header + payload, explain and warmup "
        "frames).").set_function(pod_bcast_seconds_total)


def broadcast_buckets(model, max_rows: int) -> List[int]:
    """The broadcast bucket ladder for ``model``: its engine's compile
    buckets over ``1..max_rows`` (the warmup ladder's rungs — shapes the
    mesh compiles anyway), capped at and always including ``max_rows``;
    a power-of-two ladder when the engine's batches are not bucketed."""

    from distributedkernelshap_tpu.serving.server import ExplainerServer

    max_rows = int(max_rows)
    bucket = ExplainerServer._bucket_fn(model)
    if bucket is None:
        sizes, b = {max_rows}, 1
        while b < max_rows:
            sizes.add(b)
            b *= 2
        return sorted(sizes)
    sizes = {min(int(bucket(n)), max_rows) for n in range(1, max_rows + 1)}
    sizes.add(max_rows)
    return sorted(sizes)


class MultihostServingModel:
    """Wraps a fitted serving model (``KernelShapModel``-like) so every
    device call is preceded by a header+batch broadcast to the follower
    processes.

    Parameters
    ----------
    model
        A fitted single-process serving model whose explainer was built
        with ``distributed_opts`` spanning the multi-host mesh.
    max_rows
        Broadcast slot bound: the largest batch the protocol carries.
        The server reads this attribute to reject single over-slot
        requests with 413 at enqueue time and to stop coalescing before
        a stacked batch would overflow the slot; the check in
        :meth:`explain_batch` is the backstop.  Batches are padded only
        to the smallest broadcast *bucket* that fits them, not to this
        slot.
    buckets
        Broadcast bucket ladder (sorted rung sizes, last == ``max_rows``).
        Defaults to :func:`broadcast_buckets` — the engine's compile
        rungs, so bucketing adds no new collective shapes beyond what
        warmup compiles.
    transport
        Broadcast transport; defaults to the real collective wire
        (:class:`CollectiveTransport`).  Tests inject an in-process fake.
    """

    def __init__(self, model, max_rows: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 transport=None):
        self.model = model
        self.explainer = model.explainer  # passthrough for introspection
        self.max_rows = int(max_rows)
        self._transport = transport if transport is not None \
            else _default_transport()
        # collective wires need every op shape-uniform (MTU chunking);
        # host-side wires carry frames as-is
        self._uniform_wire = bool(
            getattr(self._transport, "needs_uniform_ops", True))
        self._n_features = int(
            model.explainer._explainer.background.shape[1])
        self.buckets = sorted(int(b) for b in (
            buckets if buckets is not None
            else broadcast_buckets(model, self.max_rows)))
        if not self.buckets or self.buckets[-1] != self.max_rows:
            raise ValueError(
                f"broadcast buckets {self.buckets} must be non-empty and "
                f"end at max_rows={self.max_rows}")
        # one lock serialises EVERY lead-side broadcast: the server's
        # dispatcher thread runs explain_batch while shutdown_followers may
        # be called from the main thread — interleaved broadcasts would
        # desync the followers' header/payload pairing
        self._bcast_lock = lockwitness.make_lock("multihost.bcast")
        self._shut = False
        # drain accounting: dispatches opened (broadcast sent) but not yet
        # completed — the shutdown handshake must flush these before the
        # shutdown broadcast, or a k8s rollout strands followers (and the
        # lead's own finalizers) in half-finished collectives
        self._drain_cv = lockwitness.make_condition("multihost.drain")
        self._inflight = 0
        if not self._transport.is_lead:
            raise RuntimeError(
                "MultihostServingModel must be constructed on the lead "
                "process only; followers run follower_loop()")

    # the server treats the absence of explain_batch_async as "dispatch
    # synchronously" — exactly what the lock-step protocol needs.

    @property
    def supports_wire_formats(self) -> bool:
        # per-slot wire formats only change the LEAD's host-side response
        # encoding (wrappers._resplit_payloads) — the device program and
        # therefore the followers' collective sequence are format-blind,
        # so the capability passes straight through
        return bool(getattr(self.model, "supports_wire_formats", False))

    def _bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if b >= rows:
                return b
        return self.max_rows

    def _broadcast_batch(self, stacked: np.ndarray,
                         cmd: int = _CMD_EXPLAIN) -> np.ndarray:
        """Validate + frame + broadcast one batch (caller holds
        ``_bcast_lock``); ONE implementation of the wire protocol so the
        sync, pipelined and warmup dispatch paths cannot drift their
        framing."""

        stacked = np.atleast_2d(np.asarray(stacked, dtype=np.float32))
        rows = stacked.shape[0]
        if rows > self.max_rows:
            raise ValueError(
                f"batch of {rows} rows exceeds the multihost broadcast slot "
                f"({self.max_rows}); raise max_rows or lower max_batch_size")
        if self._shut:
            # a batch the dispatcher popped before stop(): fail it as a
            # per-request error instead of broadcasting into a mesh whose
            # followers have already exited (peerless collective =
            # permanent hang)
            raise RuntimeError("multihost serving mesh already shut down")
        bucket = self._bucket_for(rows)
        t0 = time.monotonic()
        if self._uniform_wire:
            chunk = _chunk_elems(self._n_features)
            n_chunks = _payload_chunks(bucket, self._n_features)
            header = np.zeros(chunk, np.float32)
            header[:_HEADER_LEN] = (cmd, rows, bucket)
            # bucket-padded payload, laid out as shape-uniform MTU chunks
            # (see _chunk_elems for why every wire op must be one shape)
            body = np.zeros(n_chunks * chunk, np.float32)
            body[:rows * self._n_features] = stacked.ravel()
            self._transport.broadcast(header, is_source=True)
            for i in range(n_chunks):
                self._transport.broadcast(body[i * chunk:(i + 1) * chunk],
                                          is_source=True)
            nbytes = (1 + n_chunks) * chunk * 4
        else:
            header = np.array([cmd, rows, bucket], np.float32)
            padded = np.zeros((bucket, self._n_features), np.float32)
            padded[:rows] = stacked
            self._transport.broadcast(header, is_source=True)
            self._transport.broadcast(padded, is_source=True)
            nbytes = header.nbytes + padded.nbytes
        record_pod_bcast(bucket, nbytes, time.monotonic() - t0)
        return stacked

    def _enter(self) -> None:
        with self._drain_cv:
            self._inflight += 1

    def _leave(self) -> None:
        with self._drain_cv:
            self._inflight -= 1
            if self._inflight <= 0:
                self._drain_cv.notify_all()

    def explain_batch(self, stacked: np.ndarray, split_sizes=None,
                      formats=None):
        kwargs = {} if formats is None else {"formats": formats}
        with self._bcast_lock:
            stacked = self._broadcast_batch(stacked)
            self._enter()
            try:
                return self.model.explain_batch(stacked,
                                                split_sizes=split_sizes,
                                                **kwargs)
            finally:
                self._leave()

    def warmup_batch(self, stacked: np.ndarray, split_sizes=None):
        """One collective-safe warmup rung: broadcast the rows under
        ``_CMD_WARMUP`` (followers run the SYNC explain, compiling the
        same ``rows=<b>`` signature in lockstep) and run the lead's own
        sync explain.  The server's warmup ladder calls this instead of
        :meth:`explain_batch` when present, so every process finishes its
        bucket compiles before ``/healthz`` flips ready."""

        stacked = np.atleast_2d(np.asarray(stacked, dtype=np.float32))
        flightrec().record("pod_warmup", role="lead",
                           rows=int(stacked.shape[0]),
                           bucket=self._bucket_for(int(stacked.shape[0])))
        with self._bcast_lock:
            stacked = self._broadcast_batch(stacked, cmd=_CMD_WARMUP)
            self._enter()
            try:
                return self.model.explain_batch(stacked,
                                                split_sizes=split_sizes)
            finally:
                self._leave()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until no broadcast-dispatched device call is still in
        flight (sync calls in progress, pipelined dispatches whose
        finalize has not completed).  Returns ``False`` on timeout."""

        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._drain_cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._drain_cv.wait(left)
        return True

    def drain_and_shutdown(self, server=None, grace_s: float = 30.0) -> bool:
        """The rollout-safe shutdown handshake: stop accepting (``server
        .stop()`` fails queued work with retriable 503s and parks the
        dispatcher), flush every in-flight broadcast's device call, THEN
        broadcast shutdown — so followers never exit with a half-finished
        collective pending.  Returns whether the drain completed inside
        ``grace_s`` (shutdown is broadcast either way: at the grace
        boundary a wedged collective cannot be recovered from Python and
        the deployment's liveness probe is the backstop)."""

        if server is not None:
            server.stop()
        clean = self.drain(grace_s)
        flightrec().record("pod_drain", role="lead", clean=clean,
                           grace_s=grace_s)
        if not clean:
            logger.warning(
                "pod drain did not complete within %.1fs; broadcasting "
                "shutdown with work possibly in flight", grace_s)
        self.shutdown_followers()
        return clean

    def shutdown_followers(self):
        """Release the follower loops.  Idempotent: the first call
        broadcasts the shutdown header; later calls are no-ops (a second
        broadcast would block forever — the followers are gone).  Prefer
        :meth:`drain_and_shutdown` on live deployments: broadcasting
        shutdown with dispatches still in flight is only safe because the
        broadcast order guarantees followers dispatched them first."""

        with self._bcast_lock:
            if self._shut:
                return
            self._shut = True
            # bucket=0 -> zero payload: shutdown is a header-only frame
            # (on collective wires still padded to the one MTU shape)
            if self._uniform_wire:
                header = np.zeros(_chunk_elems(self._n_features), np.float32)
                header[:_HEADER_LEN] = (_CMD_SHUTDOWN, 0, 0)
            else:
                header = np.array([_CMD_SHUTDOWN, 0, 0], np.float32)
            self._transport.broadcast(header, is_source=True)


def follower_loop(model, max_rows: int = 256, transport=None):
    """Run on every non-lead process: enter each broadcast explain call so
    the mesh collectives pair with the lead's, until shutdown.

    ``model`` must be built from the SAME constructor/fit arguments as the
    lead's (SPMD discipline — identical jitted programs and shardings),
    with the same ``max_rows``.  Payload receive buffers are allocated
    per broadcast bucket from the header's bucket field — followers need
    no ladder knowledge of their own.
    """

    transport = transport if transport is not None else _default_transport()
    if transport.is_lead:
        raise RuntimeError("follower_loop must not run on the lead process")
    rank = transport.process_index
    inner = model.explainer._explainer
    n_features = int(inner.background.shape[1])
    # pipelined protocol (replicated results): the follower only needs to
    # ENTER each device program in broadcast order — dispatch async and
    # defer the finalize (it fetches nothing the follower uses; buffers
    # free once execution completes), so the loop returns to the broadcast
    # immediately and the lead can run several calls in flight.  The LAST
    # finalize is kept: dispatches execute in order, so blocking on it at
    # shutdown proves every earlier program completed before this process
    # tears down its runtime (the lead's drain handshake mirrors this).
    pipelined = getattr(inner, 'replicate_results', False) \
        and hasattr(inner, 'get_explanation_async')
    last_fin = None
    uniform = bool(getattr(transport, "needs_uniform_ops", True))
    chunk = _chunk_elems(n_features)
    while True:
        header = transport.broadcast(
            np.zeros(chunk if uniform else _HEADER_LEN, np.float32),
            is_source=False)
        cmd = int(round(float(header[0])))
        if cmd == _CMD_SHUTDOWN:
            if last_fin is not None:
                try:
                    last_fin()
                except Exception:
                    logger.exception("follower %d: final pipelined fetch "
                                     "failed at shutdown", rank)
            flightrec().record("pod_drain", role="follower", rank=rank)
            logger.info("follower %d: shutdown", rank)
            return
        rows = int(round(float(header[1])))
        bucket = int(round(float(header[2])))
        if uniform:
            n_chunks = _payload_chunks(bucket, n_features)
            body = np.empty(n_chunks * chunk, np.float32)
            for i in range(n_chunks):
                body[i * chunk:(i + 1) * chunk] = transport.broadcast(
                    np.zeros(chunk, np.float32), is_source=False)
            padded = body[:bucket * n_features].reshape(bucket, n_features)
        else:
            padded = transport.broadcast(
                np.zeros((bucket, n_features), np.float32), is_source=False)
        if cmd == _CMD_WARMUP:
            # warmup rungs run the SYNC explain even on the pipelined
            # protocol: the point is finishing this process's compile
            # before the lead's /healthz flips, not latency
            flightrec().record("pod_warmup", role="follower", rank=rank,
                               rows=rows, bucket=bucket)
            try:
                model.explainer.explain(padded[:rows], silent=True,
                                        **model.explain_kwargs)
            except Exception:
                logger.exception("follower %d: warmup rung failed; "
                                 "staying in loop", rank)
            continue
        if pipelined:
            try:
                last_fin = inner.get_explanation_async(padded[:rows],
                                                       **model.explain_kwargs)
            except Exception:
                logger.exception(
                    "follower %d: async dispatch failed; staying in loop",
                    rank)
            continue
        # identical DEVICE call as the lead's explain_batch (explain_batch
        # == explainer.explain + host-side response building): same bucket
        # padding, same sharded program, same collective sequence — but the
        # response JSON is built on the lead only, so followers skip
        # _resplit_payloads/to_json instead of rendering and discarding it.
        try:
            model.explainer.explain(padded[:rows], silent=True,
                                    **model.explain_kwargs)
        except Exception:
            # mirror the lead's catch-and-continue (server.py answers the
            # request with a 500 and keeps serving): a data-dependent
            # explain error must degrade to one failed request, not kill
            # this loop and leave the lead's next broadcast peerless.
            # (If the error struck INSIDE a collective the mesh may be
            # unrecoverable regardless — SPMD's inherent hazard — but
            # symmetric host-side failures recover cleanly.  An ASYMMETRIC
            # lead-side failure before it enters the device call leaves
            # this loop's next collective peerless, and a blocked XLA
            # collective cannot be timed out from Python: the deployment's
            # liveness probe + termination grace period are the required
            # backstop — cluster/tpu_serve_cluster.yaml documents the
            # wiring.)
            logger.exception("follower %d: explain failed; staying in loop",
                             rank)


class PipelinedMultihostServingModel(MultihostServingModel):
    """Broadcast-protocol serving model whose device calls PIPELINE.

    Requires the wrapped model's explainer to be a ``DistributedExplainer``
    built with ``distributed_opts['replicate_results']=True``: phi/f(x)
    are then all-gathered INSIDE the jitted program, so the lead's fetch
    is a local D2H with no collective and may run on any finalizer thread
    — collective order equals dispatch order on every process by
    construction (all broadcasts + dispatches happen on the lead's single
    dispatcher thread, and the follower's loop mirrors them in the same
    order with async dispatches).  ``serve_multihost`` selects this class
    automatically (the pipelined protocol is the default production
    path); the lock-step base class remains for explainers without
    replicated results."""

    def __init__(self, model, max_rows: int = 256,
                 buckets: Optional[Sequence[int]] = None, transport=None):
        super().__init__(model, max_rows=max_rows, buckets=buckets,
                         transport=transport)
        inner = model.explainer._explainer
        if not getattr(inner, 'replicate_results', False):
            raise ValueError(
                "PipelinedMultihostServingModel needs "
                "distributed_opts['replicate_results']=True (fetches must "
                "be collective-free for pipelined finalizes)")

    def stage_rows(self, instances):
        """Staging hook so the server's PR 6 batcher runs in front of the
        pod: batches are FORMED and stacked one step ahead of dispatch on
        the batcher thread.  Returns ``None`` deliberately — the H2D (and
        the broadcast) must stay on the dispatcher thread under
        ``_bcast_lock``, because a batcher-thread broadcast could
        interleave with a concurrent shutdown broadcast and dispatch a
        program on the followers that the lead never enters."""

        return None

    def explain_batch_async(self, stacked: np.ndarray, split_sizes=None,
                            formats=None):
        kwargs = {} if formats is None else {"formats": formats}
        with self._bcast_lock:
            stacked = self._broadcast_batch(stacked)
            # dispatch INSIDE the lock: broadcast->dispatch must be atomic
            # against a concurrent shutdown broadcast, and the server's
            # single dispatcher thread is the only explain caller anyway
            fin = self.model.explain_batch_async(stacked,
                                                 split_sizes=split_sizes,
                                                 **kwargs)
            self._enter()

        def finalize():
            try:
                return fin()
            finally:
                self._leave()

        return finalize


def follower_health_server(port: int):
    """Minimal ``/healthz`` listener for follower pods.

    Followers must NOT serve the explain API (requests go to the lead), but
    a kubelet liveness probe against a port nobody listens on would kill a
    healthy follower in a restart loop.  This answers process liveness
    only — deliberately WITHOUT a device round trip: an idle follower sits
    inside ``broadcast_one_to_all``'s pending collective, so a probe op
    queued behind it would hang and misreport healthy-idle as wedged.  The
    wedge detector for the whole group is the LEAD's device-probing
    ``/healthz`` (``server.py``); its restart takes the slice down together.
    Returns the started ``ThreadingHTTPServer`` (daemon threads; caller may
    ignore it for the life of the process).
    """

    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"status": "alive", "role": "follower"}).encode()
            code = 200 if self.path.rstrip("/") == "/healthz" else 404
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            logger.debug("follower health: " + fmt, *args)

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    logger.info("follower health listener on :%d/healthz",
                httpd.server_address[1])
    return httpd


def serve_multihost(predictor, background_data, constructor_kwargs,
                    fit_kwargs, distributed_opts, host: str = "0.0.0.0",
                    port: int = 8000, max_batch_size: int = 1,
                    max_rows: int = 256,
                    explain_kwargs: Optional[dict] = None,
                    pipeline_depth: Optional[int] = 4,
                    warmup: Optional[bool] = None,
                    staging: Optional[bool] = None):
    """Entry point for every process of a multi-host serve deployment.

    On the lead process: builds the fitted model over the multi-host mesh,
    wraps it for broadcast, starts the HTTP server, and returns the server
    (caller stops it with ``model.drain_and_shutdown(server)``).
    On follower processes: starts the health listener on the same port
    (liveness/readiness probes must not kill pods that correctly serve no
    explain API), builds the identical model and blocks in
    :func:`follower_loop` until shutdown (returns None).

    The pipelined protocol is the DEFAULT: ``replicate_results`` defaults
    to True unless the caller pins it False in ``distributed_opts``
    (every process applies the same default, so the mesh stays SPMD).
    ``warmup`` defaults to the environment resolution with pods ON (like
    replica workers — restarts are routine and the ladder broadcasts as
    ``_CMD_WARMUP`` so all processes compile in lockstep before
    ``/healthz`` flips); ``staging`` defaults ON for the pipelined path
    (batch forming overlaps dispatch) and OFF for lock-step (no async
    hook to overlap with).
    """

    import jax

    from distributedkernelshap_tpu.serving.server import (
        ExplainerServer,
        resolve_warmup_env,
    )
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
        KernelShapModel,
    )

    opts = dict(distributed_opts)
    # pipelined-by-default: identical resolution on every process (the
    # base model's jitted programs must agree across the mesh)
    opts.setdefault("replicate_results", True)
    cls = BatchKernelShapModel if max_batch_size > 1 else KernelShapModel
    ctor = dict(constructor_kwargs)
    ctor["distributed_opts"] = opts
    base = cls(predictor, background_data, ctor, fit_kwargs,
               explain_kwargs=explain_kwargs)
    if jax.process_index() != 0:
        health = follower_health_server(port)
        try:
            follower_loop(base, max_rows=max_rows)
        finally:
            health.shutdown()
            health.server_close()
        return None
    pipelined = bool(opts.get("replicate_results"))
    if pipelined:
        # the deployment's explain options must actually take the async
        # fast path — otherwise every request lands in the synchronous
        # fallback inside the broadcast lock and the per-call in-program
        # all-gather is pure cost with no pipelining.  Detect it here and
        # degrade loudly to the lock-step protocol.
        inner = base.explainer._explainer
        kw = dict(base.explain_kwargs)
        if not inner.takes_async_fast_path(
                max_rows, nsamples=kw.get("nsamples"),
                l1_reg=kw.get("l1_reg", "auto"),
                interactions=bool(kw.get("interactions"))):
            logger.warning(
                "replicate_results=True but explain options (%r) route "
                "every request through the synchronous fallback (exact / "
                "interactions / active l1 selection / slab-split batches); "
                "serving LOCK-STEP instead — drop those options or set "
                "l1_reg=False to pipeline.", kw)
            pipelined = False
    if warmup is None:
        warmup = resolve_warmup_env(default=True)
    if pipelined:
        # replicated results -> collective-free fetches -> the broadcast
        # protocol pipelines at the server's calibrated depth, with the
        # staging batcher forming batches one step ahead
        model = PipelinedMultihostServingModel(base, max_rows=max_rows)
        server = ExplainerServer(model, host=host, port=port,
                                 max_batch_size=max_batch_size,
                                 pipeline_depth=pipeline_depth,
                                 warmup=warmup,
                                 staging=True if staging is None else staging)
    else:
        model = MultihostServingModel(base, max_rows=max_rows)
        server = ExplainerServer(model, host=host, port=port,
                                 max_batch_size=max_batch_size,
                                 pipeline_depth=1, warmup=warmup,
                                 staging=bool(staging))
    # chargeback: the pod's device-seconds span EVERY process's devices —
    # the SPMD program occupies all hosts for the lead-measured interval,
    # so the meter bills elapsed x process_count (billing only the lead's
    # share under-charged an N-host pod N-fold)
    server._costmeter.set_device_multiplier(jax.process_count())
    return server.start()
