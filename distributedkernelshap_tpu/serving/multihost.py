"""SPMD serving over a multi-host mesh.

The single-host server (``serving/server.py``) owns the whole device mesh
from one process.  On a multi-host mesh (``jax.distributed`` across
TPU-VM workers — the reference analog is Ray Serve replicas spread over the
k8s cluster, ``cluster/ray_cluster.yaml:119-141``) a device call is a
*collective* program: every process must enter the same sharded computation
in the same order, but HTTP requests arrive only at the lead process.

The bridge is a broadcast protocol, the serving-plane counterpart of the
SPMD benchmark drivers (``benchmarks/multihost_pool.py``): the lead process
runs the normal :class:`~distributedkernelshap_tpu.serving.server.ExplainerServer`
around a :class:`MultihostServingModel`, which prefixes every device call
with ``multihost_utils.broadcast_one_to_all`` of a fixed-shape header +
padded batch; follower processes sit in :func:`follower_loop`, receive each
broadcast, and enter the identical explain call so the mesh's collectives
line up.  Responses are built on the lead only (host-side work, no
collectives).  Shutdown is a zero header broadcast.

Pipelining: the base protocol is lock-step (one device call at a time —
the model does not expose ``explain_batch_async``, the server dispatches
synchronously, ``pipeline_depth`` is 1), because a sharded fetch embeds a
``process_allgather`` whose cross-process order concurrent finalizes would
scramble.  With ``distributed_opts['replicate_results']=True`` the
all-gather moves INSIDE the jitted program, fetches become local, and
:class:`PipelinedMultihostServingModel` + the follower's async dispatch
run several broadcast+explain calls in flight at the server's pipeline
depth — collective order equals dispatch order on every process by
construction.  Within one batch the device work is always fully sharded
across all hosts' devices either way.
"""

import logging
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_CMD_SHUTDOWN = 0
_CMD_EXPLAIN = 1


def _broadcast(value, is_source: bool):
    import jax
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(
        value, is_source=is_source if jax.process_count() > 1 else True))


class MultihostServingModel:
    """Wraps a fitted serving model (``KernelShapModel``-like) so every
    device call is preceded by a header+batch broadcast to the follower
    processes.

    Parameters
    ----------
    model
        A fitted single-process serving model whose explainer was built
        with ``distributed_opts`` spanning the multi-host mesh.
    max_rows
        Broadcast slot size: every batch is padded to this many rows (the
        collective needs one static shape on all processes).  The server
        reads this attribute to reject single over-slot requests with 413
        at enqueue time and to stop coalescing before a stacked batch
        would overflow the slot; the check in :meth:`explain_batch` is the
        backstop.
    """

    def __init__(self, model, max_rows: int = 256):
        import jax

        self.model = model
        self.explainer = model.explainer  # passthrough for introspection
        self.max_rows = int(max_rows)
        self._n_features = int(
            model.explainer._explainer.background.shape[1])
        # one lock serialises EVERY lead-side broadcast: the server's
        # dispatcher thread runs explain_batch while shutdown_followers may
        # be called from the main thread — interleaved broadcasts would
        # desync the followers' header/payload pairing
        self._bcast_lock = threading.Lock()
        self._shut = False
        self._is_lead = jax.process_index() == 0
        if not self._is_lead:
            raise RuntimeError(
                "MultihostServingModel must be constructed on the lead "
                "process only; followers run follower_loop()")

    # the server treats the absence of explain_batch_async as "dispatch
    # synchronously" — exactly what the lock-step protocol needs.

    def _broadcast_batch(self, stacked: np.ndarray) -> np.ndarray:
        """Validate + frame + broadcast one batch (caller holds
        ``_bcast_lock``); ONE implementation of the wire protocol so the
        sync and pipelined dispatch paths cannot drift their framing."""

        stacked = np.atleast_2d(np.asarray(stacked, dtype=np.float32))
        rows = stacked.shape[0]
        if rows > self.max_rows:
            raise ValueError(
                f"batch of {rows} rows exceeds the multihost broadcast slot "
                f"({self.max_rows}); raise max_rows or lower max_batch_size")
        if self._shut:
            # a batch the dispatcher popped before stop(): fail it as a
            # per-request error instead of broadcasting into a mesh whose
            # followers have already exited (peerless collective =
            # permanent hang)
            raise RuntimeError("multihost serving mesh already shut down")
        header = np.array([_CMD_EXPLAIN, rows], np.int32)
        padded = np.zeros((self.max_rows, self._n_features), np.float32)
        padded[:rows] = stacked
        _broadcast(header, is_source=True)
        _broadcast(padded, is_source=True)
        return stacked

    def explain_batch(self, stacked: np.ndarray, split_sizes=None):
        with self._bcast_lock:
            stacked = self._broadcast_batch(stacked)
            return self.model.explain_batch(stacked, split_sizes=split_sizes)

    def shutdown_followers(self):
        """Release the follower loops.  Idempotent: the first call
        broadcasts the shutdown header; later calls are no-ops (a second
        broadcast would block forever — the followers are gone)."""

        with self._bcast_lock:
            if self._shut:
                return
            self._shut = True
            _broadcast(np.array([_CMD_SHUTDOWN, 0], np.int32), is_source=True)


def follower_loop(model, max_rows: int = 256):
    """Run on every non-lead process: enter each broadcast explain call so
    the mesh collectives pair with the lead's, until shutdown.

    ``model`` must be built from the SAME constructor/fit arguments as the
    lead's (SPMD discipline — identical jitted programs and shardings),
    with the same ``max_rows``.
    """

    import jax

    if jax.process_index() == 0:
        raise RuntimeError("follower_loop must not run on the lead process")
    inner = model.explainer._explainer
    n_features = int(inner.background.shape[1])
    # pipelined protocol (replicated results): the follower only needs to
    # ENTER each device program in broadcast order — dispatch async and
    # drop the finalize (it fetches nothing the follower uses; buffers free
    # once execution completes), so the loop returns to the broadcast
    # immediately and the lead can run several calls in flight
    pipelined = getattr(inner, 'replicate_results', False) \
        and hasattr(inner, 'get_explanation_async')
    while True:
        header = _broadcast(np.zeros(2, np.int32), is_source=False)
        if int(header[0]) == _CMD_SHUTDOWN:
            logger.info("follower %d: shutdown", jax.process_index())
            return
        rows = int(header[1])
        padded = _broadcast(np.zeros((max_rows, n_features), np.float32),
                            is_source=False)
        if pipelined:
            try:
                inner.get_explanation_async(padded[:rows],
                                            **model.explain_kwargs)
            except Exception:
                logger.exception(
                    "follower %d: async dispatch failed; staying in loop",
                    jax.process_index())
            continue
        # identical DEVICE call as the lead's explain_batch (explain_batch
        # == explainer.explain + host-side response building): same bucket
        # padding, same sharded program, same collective sequence — but the
        # response JSON is built on the lead only, so followers skip
        # _resplit_payloads/to_json instead of rendering and discarding it.
        try:
            model.explainer.explain(padded[:rows], silent=True,
                                    **model.explain_kwargs)
        except Exception:
            # mirror the lead's catch-and-continue (server.py answers the
            # request with a 500 and keeps serving): a data-dependent
            # explain error must degrade to one failed request, not kill
            # this loop and leave the lead's next broadcast peerless.
            # (If the error struck INSIDE a collective the mesh may be
            # unrecoverable regardless — SPMD's inherent hazard — but
            # symmetric host-side failures recover cleanly.  An ASYMMETRIC
            # lead-side failure before it enters the device call leaves
            # this loop's next collective peerless, and a blocked XLA
            # collective cannot be timed out from Python: the deployment's
            # liveness probe + termination grace period are the required
            # backstop — cluster/tpu_serve_cluster.yaml documents the
            # wiring.)
            logger.exception("follower %d: explain failed; staying in loop",
                             jax.process_index())


class PipelinedMultihostServingModel(MultihostServingModel):
    """Broadcast-protocol serving model whose device calls PIPELINE.

    Requires the wrapped model's explainer to be a ``DistributedExplainer``
    built with ``distributed_opts['replicate_results']=True``: phi/f(x)
    are then all-gathered INSIDE the jitted program, so the lead's fetch
    is a local D2H with no collective and may run on any finalizer thread
    — collective order equals dispatch order on every process by
    construction (all broadcasts + dispatches happen on the lead's single
    dispatcher thread, and the follower's loop mirrors them in the same
    order with async dispatches).  ``serve_multihost`` selects this class
    automatically; the lock-step base class remains for explainers without
    replicated results."""

    def __init__(self, model, max_rows: int = 256):
        super().__init__(model, max_rows=max_rows)
        inner = model.explainer._explainer
        if not getattr(inner, 'replicate_results', False):
            raise ValueError(
                "PipelinedMultihostServingModel needs "
                "distributed_opts['replicate_results']=True (fetches must "
                "be collective-free for pipelined finalizes)")

    def explain_batch_async(self, stacked: np.ndarray, split_sizes=None):
        with self._bcast_lock:
            stacked = self._broadcast_batch(stacked)
            # dispatch INSIDE the lock: broadcast->dispatch must be atomic
            # against a concurrent shutdown broadcast, and the server's
            # single dispatcher thread is the only explain caller anyway
            return self.model.explain_batch_async(stacked,
                                                  split_sizes=split_sizes)


def follower_health_server(port: int):
    """Minimal ``/healthz`` listener for follower pods.

    Followers must NOT serve the explain API (requests go to the lead), but
    a kubelet liveness probe against a port nobody listens on would kill a
    healthy follower in a restart loop.  This answers process liveness
    only — deliberately WITHOUT a device round trip: an idle follower sits
    inside ``broadcast_one_to_all``'s pending collective, so a probe op
    queued behind it would hang and misreport healthy-idle as wedged.  The
    wedge detector for the whole group is the LEAD's device-probing
    ``/healthz`` (``server.py``); its restart takes the slice down together.
    Returns the started ``ThreadingHTTPServer`` (daemon threads; caller may
    ignore it for the life of the process).
    """

    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"status": "alive", "role": "follower"}).encode()
            code = 200 if self.path.rstrip("/") == "/healthz" else 404
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            logger.debug("follower health: " + fmt, *args)

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    logger.info("follower health listener on :%d/healthz",
                httpd.server_address[1])
    return httpd


def serve_multihost(predictor, background_data, constructor_kwargs,
                    fit_kwargs, distributed_opts, host: str = "0.0.0.0",
                    port: int = 8000, max_batch_size: int = 1,
                    max_rows: int = 256,
                    explain_kwargs: Optional[dict] = None,
                    pipeline_depth: Optional[int] = 4):
    """Entry point for every process of a multi-host serve deployment.

    On the lead process: builds the fitted model over the multi-host mesh,
    wraps it for broadcast, starts the HTTP server, and returns the server
    (caller stops it with ``.stop()`` then ``model.shutdown_followers()``).
    On follower processes: starts the health listener on the same port
    (liveness/readiness probes must not kill pods that correctly serve no
    explain API), builds the identical model and blocks in
    :func:`follower_loop` until shutdown (returns None).
    """

    import jax

    from distributedkernelshap_tpu.serving.server import ExplainerServer
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
        KernelShapModel,
    )

    cls = BatchKernelShapModel if max_batch_size > 1 else KernelShapModel
    ctor = dict(constructor_kwargs)
    ctor["distributed_opts"] = dict(distributed_opts)
    base = cls(predictor, background_data, ctor, fit_kwargs,
               explain_kwargs=explain_kwargs)
    if jax.process_index() != 0:
        health = follower_health_server(port)
        try:
            follower_loop(base, max_rows=max_rows)
        finally:
            health.shutdown()
            health.server_close()
        return None
    pipelined = bool(dict(distributed_opts).get("replicate_results"))
    if pipelined:
        # the deployment's explain options must actually take the async
        # fast path — otherwise every request lands in the synchronous
        # fallback inside the broadcast lock and the per-call in-program
        # all-gather is pure cost with no pipelining.  Detect it here and
        # degrade loudly to the lock-step protocol.
        inner = base.explainer._explainer
        kw = dict(base.explain_kwargs)
        if not inner.takes_async_fast_path(
                max_rows, nsamples=kw.get("nsamples"),
                l1_reg=kw.get("l1_reg", "auto"),
                interactions=bool(kw.get("interactions"))):
            logger.warning(
                "replicate_results=True but explain options (%r) route "
                "every request through the synchronous fallback (exact / "
                "interactions / active l1 selection / slab-split batches); "
                "serving LOCK-STEP instead — drop those options or set "
                "l1_reg=False to pipeline.", kw)
            pipelined = False
    if pipelined:
        # replicated results -> collective-free fetches -> the broadcast
        # protocol pipelines at the server's calibrated depth
        model = PipelinedMultihostServingModel(base, max_rows=max_rows)
        server = ExplainerServer(model, host=host, port=port,
                                 max_batch_size=max_batch_size,
                                 pipeline_depth=pipeline_depth)
    else:
        model = MultihostServingModel(base, max_rows=max_rows)
        server = ExplainerServer(model, host=host, port=port,
                                 max_batch_size=max_batch_size,
                                 pipeline_depth=1)
    return server.start()
