"""Serving entry point: fit the default Adult explainer and serve it.

``python -m distributedkernelshap_tpu.serving.main`` is what the k8s serving
deployment runs per pod (cluster/tpu_serve_cluster.yaml) — the analog of the
reference's in-cluster backend setup (``benchmarks/serve_explanations.py:42-67``)
minus the Serve controller.
"""

import argparse
import logging
import signal
import threading

from distributedkernelshap_tpu.serving.server import serve_explainer

logging.basicConfig(level=logging.INFO)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", default=8000, type=int)
    parser.add_argument("--max_batch_size", default=32, type=int)
    parser.add_argument("--pipeline_depth", default=0, type=int,
                        help="In-flight device batches (overlapped D2H); the "
                             "reference's num_replicas analog. 0 (default) "
                             "self-calibrates at startup.")
    parser.add_argument("--checkpoint", default=None, type=str,
                        help="Serve a saved explainer (KernelShap.save) "
                             "instead of fitting the default Adult one.")
    parser.add_argument("--exact", action="store_true",
                        help="Serve exact interventional TreeSHAP responses "
                             "(lifted tree ensembles with raw-margin outputs "
                             "and link='identity' only; ops/treeshap.py).")
    parser.add_argument("--coordinator", default=None, type=str,
                        help="Multi-host: jax.distributed coordinator "
                             "address.  All pods run this entry; process 0 "
                             "serves HTTP, the rest join each device call "
                             "via the broadcast protocol "
                             "(serving/multihost.py).")
    parser.add_argument("--num_processes", default=None, type=int)
    parser.add_argument("--process_id", default=None, type=int)
    parser.add_argument("--max_rows", default=None, type=int,
                        help="Multi-host broadcast slot (rows per stacked "
                             "batch); default 256.")
    parser.add_argument("--replicate_results", action="store_true",
                        help="Multi-host only: all-gather results inside "
                             "the jitted program so the broadcast protocol "
                             "PIPELINES device calls (serving/multihost.py)."
                             " Now the DEFAULT production path; kept as an "
                             "explicit no-op for compatibility — see "
                             "--lockstep for the opt-out.")
    parser.add_argument("--lockstep", action="store_true",
                        help="Multi-host only: opt OUT of the pipelined "
                             "default (replicate_results=False) and serve "
                             "one device call at a time.")
    parser.add_argument("--coalition_parallel", default=1, type=int,
                        help="Multi-host only: shard the hot path 2D "
                             "(batch x coalition) across the pod's mesh. "
                             "Needs jax.shard_map (JAX >= 0.6) on "
                             "multi-process meshes; old JAX rejects it "
                             "loudly (parallel/mesh.py).")
    parser.add_argument("--factory", default=None, type=str,
                        help="module:function returning (predictor, "
                             "background, ctor_kwargs, fit_kwargs) — the "
                             "replica workers' deployment tuple, honoured "
                             "by every serving mode incl. --coordinator "
                             "pods (default: the Adult deployment).")
    parser.add_argument("--pod_procs", default=1, type=int,
                        help="With --replica_procs: processes per replica "
                             "UNIT — each replica becomes a multi-host pod "
                             "(lead + followers over a local coordinator) "
                             "that the proxy/supervisor/autoscaler treat "
                             "as one citizen (serving/replicas.py).")
    parser.add_argument("--replica_procs", default=0, type=int,
                        help="Replica-per-chip mode: spawn this many "
                             "crash-isolated single-device server PROCESSES "
                             "(each pinned to one chip) behind a fan-in "
                             "proxy on --port (serving/replicas.py) — the "
                             "reference's num_replicas crash independence "
                             "where the hardware allows it.")
    args = parser.parse_args()
    explain_kwargs = {"nsamples": "exact"} if args.exact else None

    if args.coordinator is None and (args.num_processes is not None
                                     or args.process_id is not None):
        parser.error("--num_processes/--process_id require --coordinator "
                     "(a would-be follower must never start its own server)")

    def _load_deployment_args():
        # ONE definition of the deployment tuple, shared with the replica
        # workers so --replica_procs / --coordinator pods can never serve
        # a different explainer than the single-process modes: an explicit
        # --factory wins, then --checkpoint (rebuilt through the ctor
        # tuple so every pod process re-fits identically), else the
        # default Adult deployment
        from distributedkernelshap_tpu.serving.replica_worker import (
            adult_factory,
            checkpoint_factory,
            resolve_factory,
        )

        if args.factory:
            return resolve_factory(args.factory)()
        if args.checkpoint:
            return checkpoint_factory(args.checkpoint)
        return adult_factory()

    if args.pod_procs < 1:
        parser.error("--pod_procs must be >= 1")
    if args.pod_procs > 1 and not args.replica_procs:
        parser.error("--pod_procs sizes the replica UNITS of the "
                     "--replica_procs fleet; a standalone pod is "
                     "--coordinator with one process per host")
    if args.replicate_results and args.lockstep:
        parser.error("--replicate_results and --lockstep are opposites")
    if args.factory and args.checkpoint:
        parser.error("--factory and --checkpoint both name a deployment; "
                     "pick one")

    if args.replica_procs:
        if args.coordinator is not None or args.checkpoint or args.exact \
                or args.replicate_results or args.lockstep \
                or args.max_rows is not None:
            # fail loudly, same convention as the multihost branch: a flag
            # this mode cannot honour must never be silently dropped
            # (--pod_procs composes: each replica unit becomes a pod)
            parser.error("--replica_procs is the single-host replica "
                         "fleet mode; it does not combine with "
                         "--coordinator/--checkpoint/--exact/"
                         "--replicate_results/--lockstep/--max_rows")
        from distributedkernelshap_tpu.serving.replica_worker import (
            adult_factory,
        )
        from distributedkernelshap_tpu.serving.replicas import ReplicaManager

        manager = ReplicaManager(
            args.replica_procs,
            factory=args.factory or (adult_factory.__module__
                                     + ":adult_factory"),
            max_batch_size=args.max_batch_size,
            pipeline_depth=args.pipeline_depth or None,
            pod_processes=args.pod_procs,
        ).start(proxy_port=args.port, proxy_host=args.host)
        unit = ("pods" if args.pod_procs > 1 else "worker processes")
        banner = (f"replica serving on "
                  f"{manager.proxy.host}:{manager.proxy.port} "
                  f"({args.replica_procs} {unit}"
                  + (f" x {args.pod_procs} processes" if args.pod_procs > 1
                     else "") + ")")
        on_stop = manager.stop
    elif args.coordinator is not None:
        # multi-host deployment: every pod process runs this same entry
        # (SPMD).  Followers block inside serve_multihost until the
        # shutdown broadcast.  --checkpoint/--exact/--factory all route
        # through the same ctor-tuple loading the replica workers use, so
        # any deployment — tree/TT/deepshap engine paths included —
        # serves from a pod.
        # a pod-wide SIGTERM (k8s rollout) must not kill followers before
        # the lead broadcasts shutdown — their orderly exit IS the shutdown
        # broadcast.  The rank may be auto-inferred (unknown until after
        # init), so EVERY process ignores the signals first; the lead
        # reinstalls its stop handlers at the shared block below once
        # serve_multihost identifies it.  If the lead dies hard, k8s
        # SIGKILLs the followers at the grace-period boundary.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)

        import jax

        from distributedkernelshap_tpu.parallel.mesh import initialize_multihost
        from distributedkernelshap_tpu.serving.multihost import serve_multihost

        initialize_multihost(args.coordinator, args.num_processes,
                             args.process_id)
        predictor, background, ctor_kwargs, fit_kwargs = \
            _load_deployment_args()
        opts = {"n_devices": len(jax.devices())}
        if args.coalition_parallel > 1:
            # 2D sharding (batch x coalition) across the pod; on JAX too
            # old for multi-process shard_map the mesh builder rejects it
            # loudly with the upgrade hint (parallel/mesh.py)
            opts["coalition_parallel"] = args.coalition_parallel
        if args.lockstep:
            opts["replicate_results"] = False
        # pipelined (replicate_results=True) is serve_multihost's default
        server = serve_multihost(
            predictor, background, ctor_kwargs, fit_kwargs, opts,
            host=args.host, port=args.port,
            max_batch_size=args.max_batch_size,
            max_rows=args.max_rows if args.max_rows is not None else 256,
            explain_kwargs=explain_kwargs,
            pipeline_depth=args.pipeline_depth or None,
        )
        if server is None:
            logging.info("follower %d released; exiting", jax.process_index())
            return
        banner = (f"multi-host serving on {server.host}:{server.port} "
                  f"(lead of {jax.process_count()} processes)")

        def on_stop():
            # drain handshake: stop accepting, flush in-flight broadcast
            # dispatches, THEN broadcast shutdown — a k8s rollout must
            # never strand followers in a half-finished collective
            server.model.drain_and_shutdown(server)
    elif args.checkpoint:
        from distributedkernelshap_tpu.kernel_shap import KernelShap
        from distributedkernelshap_tpu.serving.server import ExplainerServer
        from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

        explainer = KernelShap.load(args.checkpoint)
        model = BatchKernelShapModel.from_explainer(explainer,
                                                    explain_kwargs=explain_kwargs)
        server = ExplainerServer(model, host=args.host, port=args.port,
                                 max_batch_size=args.max_batch_size,
                                 pipeline_depth=args.pipeline_depth or None).start()
        banner = f"serving on {server.host}:{server.port} — Ctrl-C to stop"
        on_stop = server.stop
    else:
        predictor, background, ctor_kwargs, fit_kwargs = \
            _load_deployment_args()
        server = serve_explainer(
            predictor, background, ctor_kwargs, fit_kwargs,
            host=args.host, port=args.port, max_batch_size=args.max_batch_size,
            pipeline_depth=args.pipeline_depth or None,
            explain_kwargs=explain_kwargs,
        )
        banner = f"serving on {server.host}:{server.port} — Ctrl-C to stop"
        on_stop = server.stop

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    logging.info(banner)
    stop.wait()
    on_stop()


if __name__ == "__main__":
    main()
