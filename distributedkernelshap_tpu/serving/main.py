"""Serving entry point: fit the default Adult explainer and serve it.

``python -m distributedkernelshap_tpu.serving.main`` is what the k8s serving
deployment runs per pod (cluster/tpu_serve_cluster.yaml) — the analog of the
reference's in-cluster backend setup (``benchmarks/serve_explanations.py:42-67``)
minus the Serve controller.
"""

import argparse
import logging
import signal
import threading

from distributedkernelshap_tpu.serving.server import serve_explainer
from distributedkernelshap_tpu.utils import load_data, load_model

logging.basicConfig(level=logging.INFO)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", default=8000, type=int)
    parser.add_argument("--max_batch_size", default=32, type=int)
    parser.add_argument("--pipeline_depth", default=0, type=int,
                        help="In-flight device batches (overlapped D2H); the "
                             "reference's num_replicas analog. 0 (default) "
                             "self-calibrates at startup.")
    parser.add_argument("--checkpoint", default=None, type=str,
                        help="Serve a saved explainer (KernelShap.save) "
                             "instead of fitting the default Adult one.")
    parser.add_argument("--exact", action="store_true",
                        help="Serve exact interventional TreeSHAP responses "
                             "(lifted tree ensembles with raw-margin outputs "
                             "and link='identity' only; ops/treeshap.py).")
    args = parser.parse_args()
    explain_kwargs = {"nsamples": "exact"} if args.exact else None

    if args.checkpoint:
        from distributedkernelshap_tpu.kernel_shap import KernelShap
        from distributedkernelshap_tpu.serving.server import ExplainerServer
        from distributedkernelshap_tpu.serving.wrappers import BatchKernelShapModel

        explainer = KernelShap.load(args.checkpoint)
        model = BatchKernelShapModel.from_explainer(explainer,
                                                    explain_kwargs=explain_kwargs)
        server = ExplainerServer(model, host=args.host, port=args.port,
                                 max_batch_size=args.max_batch_size,
                                 pipeline_depth=args.pipeline_depth or None).start()
    else:
        data = load_data()
        predictor = load_model()
        group_names, groups = data["all"]["group_names"], data["all"]["groups"]
        server = serve_explainer(
            predictor,
            data["background"]["X"]["preprocessed"],
            {"link": "logit", "feature_names": group_names, "seed": 0},
            {"group_names": group_names, "groups": groups},
            host=args.host, port=args.port, max_batch_size=args.max_batch_size,
            pipeline_depth=args.pipeline_depth or None,
            explain_kwargs=explain_kwargs,
        )

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    logging.info("serving on %s:%d — Ctrl-C to stop", server.host, server.port)
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
