from distributedkernelshap_tpu.serving.wrappers import (  # noqa: F401
    BatchKernelShapModel,
    KernelShapModel,
)
from distributedkernelshap_tpu.serving.server import ExplainerServer, serve_explainer  # noqa: F401
from distributedkernelshap_tpu.serving.client import distribute_requests, explain_request  # noqa: F401
from distributedkernelshap_tpu.serving.multihost import (  # noqa: F401
    MultihostServingModel,
    follower_loop,
    serve_multihost,
)
