"""Binary batch wire format for the streaming hot path.

PR 5 left small-B linear requests at ~2.7 ms of device work, so per-request
cost became the Python/HTTP plumbing around it: ``json.dumps({"array":
x.tolist()})`` on the client, ``json.loads`` + float-list re-materialisation
on the server, and a full ``Explanation.to_json`` per answered request.  This
module is the wire half of killing that overhead (ISSUE 6; ROADMAP open item
3, grounded in the Gemma-on-TPU host-overhead analysis, PAPERS.md arXiv
2605.25645): a versioned little-endian binary framing whose payloads are the
raw row bytes — the server ingests them with ``np.frombuffer`` (zero copy)
and the response rides raw ``phi`` bytes instead of a JSON document.

Framing (all integers little-endian)::

    message  := magic(4s="DKSW") version(u16) n_arrays(u16) array*
    array    := name_len(u16) name(utf-8) dtype(u8) ndim(u8)
                shape(ndim x u32) payload(raw C-order bytes)

``dtype`` is a code from :data:`DTYPE_CODES` (f32/f64/f16/i32/i64/u8/bool);
the payload length is implied by shape x itemsize, so a torn body is
detected by running off the end of the buffer (:class:`WireError`, which the
server maps to 400 — never a crash).  A version the decoder does not speak
raises :class:`WireVersionError` (server: 415), which is the client's
downgrade-to-JSON signal.

Negotiation is standard HTTP content negotiation so pre-existing JSON
clients keep working unchanged:

* request: ``Content-Type: application/x-dks-wire`` marks a binary body
  (anything else is parsed as the historical JSON ``{"array": ...}``);
* response: the client asks with ``Accept: application/x-dks-wire`` and the
  server answers binary only when it can — otherwise the response is the
  historical Explanation JSON and the client falls back on the response's
  own ``Content-Type``.

Parsing cost: decoding a binary batch is one ``np.frombuffer`` view —
measured >=100x cheaper than ``json.loads`` + ``np.asarray`` for float64
rows at realistic widths (see ``tests/test_streaming.py``'s roundtrip and
``benchmarks/streaming_bench.py`` for the end-to-end effect).
"""

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

#: media type negotiated for both directions
CONTENT_TYPE = "application/x-dks-wire"
#: protocol version this build speaks (encoder always emits it)
WIRE_VERSION = 1
#: human-readable protocol name recorded by benchmarks
WIRE_FORMAT_NAME = f"dks-wire-v{WIRE_VERSION}"

_MAGIC = b"DKSW"
_HEADER = struct.Struct("<4sHH")          # magic, version, n_arrays
_ARRAY_HEADER = struct.Struct("<HBB")     # name_len, dtype code, ndim

#: dtype code space (u8).  Codes are part of the wire contract — append,
#: never renumber.
DTYPE_CODES = {
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.float16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.uint8): 6,
    np.dtype(np.bool_): 7,
}
_CODE_DTYPES = {code: dt for dt, code in DTYPE_CODES.items()}

#: sanity bound on dims per array (a garbled ndim byte must not drive a
#: 255-iteration shape read off plausible data)
_MAX_NDIM = 8


class WireError(ValueError):
    """Malformed binary message (bad magic, bad dtype, truncated header,
    torn body).  The server answers 400 — a hostile or corrupt body must
    never crash a handler."""


class WireVersionError(WireError):
    """Well-formed framing but a protocol version this decoder does not
    speak.  The server answers 415; clients downgrade to JSON on it."""


def encode_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    """Encode named arrays into one binary message (see module doc for the
    framing).  Arrays are emitted C-contiguous; field order is preserved."""

    parts: List[bytes] = [_HEADER.pack(_MAGIC, WIRE_VERSION, len(arrays))]
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        code = DTYPE_CODES.get(arr.dtype)
        if code is None:
            raise WireError(f"dtype {arr.dtype} has no wire code "
                            f"(supported: {sorted(map(str, DTYPE_CODES))})")
        if arr.ndim > _MAX_NDIM:
            raise WireError(f"array {name!r} has {arr.ndim} dims "
                            f"(wire cap: {_MAX_NDIM})")
        name_b = name.encode("utf-8")
        parts.append(_ARRAY_HEADER.pack(len(name_b), code, arr.ndim))
        parts.append(name_b)
        parts.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        parts.append(arr.tobytes())
    return b"".join(parts)


def decode_arrays(buf: bytes) -> Dict[str, np.ndarray]:
    """Decode one binary message into ``{name: array}``.

    Array payloads are **zero-copy** ``np.frombuffer`` views into ``buf``
    (read-only — callers that mutate must copy; the serving ingest path
    only concatenates/uploads, which copies anyway).  Raises
    :class:`WireError` on any malformation, :class:`WireVersionError` on a
    version mismatch.
    """

    buf = memoryview(bytes(buf) if not isinstance(buf, (bytes, bytearray,
                                                        memoryview))
                     else buf)
    if len(buf) < _HEADER.size:
        raise WireError(f"truncated header: {len(buf)} bytes "
                        f"(need {_HEADER.size})")
    magic, version, n_arrays = _HEADER.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise WireError(f"bad magic {bytes(magic)!r} (expected {_MAGIC!r})")
    if version != WIRE_VERSION:
        raise WireVersionError(
            f"wire version {version} not supported "
            f"(this build speaks v{WIRE_VERSION})")
    offset = _HEADER.size
    out: Dict[str, np.ndarray] = {}
    for _ in range(n_arrays):
        if offset + _ARRAY_HEADER.size > len(buf):
            raise WireError("truncated array header")
        name_len, code, ndim = _ARRAY_HEADER.unpack_from(buf, offset)
        offset += _ARRAY_HEADER.size
        if ndim > _MAX_NDIM:
            raise WireError(f"array has {ndim} dims (wire cap: {_MAX_NDIM})")
        if offset + name_len + 4 * ndim > len(buf):
            raise WireError("truncated array name/shape")
        name = bytes(buf[offset:offset + name_len]).decode("utf-8", "replace")
        offset += name_len
        shape = struct.unpack_from(f"<{ndim}I", buf, offset)
        offset += 4 * ndim
        dtype = _CODE_DTYPES.get(code)
        if dtype is None:
            raise WireError(f"unknown dtype code {code} for array {name!r}")
        count = 1
        for dim in shape:
            count *= int(dim)
        nbytes = count * dtype.itemsize
        if offset + nbytes > len(buf):
            raise WireError(
                f"torn body: array {name!r} needs {nbytes} payload bytes, "
                f"{len(buf) - offset} remain")
        out[name] = np.frombuffer(buf, dtype=dtype, count=count,
                                  offset=offset).reshape(shape)
        offset += nbytes
    if offset != len(buf):
        raise WireError(f"{len(buf) - offset} trailing bytes after the "
                        f"declared arrays")
    return out


# --------------------------------------------------------------------- #
# request / response payload helpers


def encode_request(instance: np.ndarray,
                   model_id: Optional[str] = None) -> bytes:
    """Binary /explain request body: the instance rows as float32, plus
    an optional ``model`` field (utf-8 bytes as a u8 array) naming the
    registry tenant the request targets — the wire twin of the
    ``X-DKS-Model`` header / JSON ``model`` key.  Decoders without
    registry support ignore the extra field, so the framing is
    backward-compatible."""

    arr = np.atleast_2d(np.asarray(instance, dtype=np.float32))
    arrays = {"array": arr}
    if model_id:
        arrays["model"] = np.frombuffer(model_id.encode("utf-8"),
                                        dtype=np.uint8)
    return encode_arrays(arrays)


def decode_request_meta(body: bytes):
    """``(array, model_id)`` for a binary /explain request —
    ``model_id`` is ``None`` when the body names no tenant."""

    arrays = decode_arrays(body)
    if "array" not in arrays:
        raise WireError("binary request carries no 'array' field")
    model_id = None
    if "model" in arrays:
        field = np.asarray(arrays["model"])
        if field.dtype != np.uint8 or field.ndim != 1:
            raise WireError(
                f"'model' field must be a 1-D u8 utf-8 string, got "
                f"{field.dtype} with shape {field.shape}")
        model_id = field.tobytes().decode("utf-8", "replace")
    return _check_instances(arrays["array"]), model_id


def decode_request(body: bytes) -> np.ndarray:
    """Decode a binary /explain request body into the ``(B, D)`` float32
    instance array — a zero-copy view when the body already carries
    float32 (the client encoder always does)."""

    return decode_request_meta(body)[0]


def _check_instances(arr: np.ndarray) -> np.ndarray:
    if not np.issubdtype(arr.dtype, np.floating) and \
            not np.issubdtype(arr.dtype, np.integer):
        raise WireError(f"instance rows must be numeric, got {arr.dtype}")
    arr = np.atleast_2d(arr)
    if arr.ndim != 2:
        raise WireError(f"instance rows must be 2-D, got shape {arr.shape}")
    if arr.dtype != np.float32:
        arr = arr.astype(np.float32)
    return arr


def encode_explanation(shap_values, expected_value, raw_prediction,
                       interaction_values=None) -> bytes:
    """Binary /explain response body.

    ``shap_values`` is the per-class list of ``(B, M)`` arrays (or one
    array for scalar-output models) — packed as one ``(K, B, M)`` float32
    tensor; ``expected_value`` is ``(K,)``; ``raw_prediction`` ``(B, K)``
    in link space.  ``interaction_values`` (exact TreeSHAP deployments)
    packs as ``(K, B, M, M)`` when present.  This is the full numeric
    content of the Explanation JSON's hot fields — metadata stays with the
    deployment, not on every response.
    """

    sv = shap_values if isinstance(shap_values, (list, tuple)) \
        else [shap_values]
    arrays = {
        "shap_values": np.stack([np.atleast_2d(np.asarray(v, np.float32))
                                 for v in sv]),
        "expected_value": np.atleast_1d(
            np.asarray(expected_value, np.float32)),
        "raw_prediction": np.atleast_2d(
            np.asarray(raw_prediction, np.float32)),
    }
    if interaction_values is not None:
        arrays["interaction_values"] = np.stack(
            [np.asarray(v, np.float32) for v in interaction_values])
    return encode_arrays(arrays)


def decode_explanation(body: bytes) -> Dict[str, np.ndarray]:
    """Decode a binary /explain response into
    ``{'shap_values': [K x (B, M)], 'expected_value': (K,),
    'raw_prediction': (B, K)[, 'interaction_values': [K x (B, M, M)]]}``
    — the same structure :func:`explanation_payload_from_json` extracts
    from a JSON response, so callers are transport-agnostic."""

    arrays = decode_arrays(body)
    for key in ("shap_values", "expected_value", "raw_prediction"):
        if key not in arrays:
            raise WireError(f"binary response carries no {key!r} field")
    out = {
        "shap_values": [np.asarray(v) for v in arrays["shap_values"]],
        "expected_value": np.asarray(arrays["expected_value"]),
        "raw_prediction": np.asarray(arrays["raw_prediction"]),
    }
    if "interaction_values" in arrays:
        out["interaction_values"] = [np.asarray(v)
                                     for v in arrays["interaction_values"]]
    return out


def explanation_payload_from_json(payload: str) -> Dict[str, np.ndarray]:
    """Extract the :func:`decode_explanation` structure from a JSON
    Explanation payload (``interface.Explanation.to_json`` schema) — the
    client's downgrade path, so binary-mode callers get one return shape
    whatever transport the negotiation landed on."""

    import json

    doc = json.loads(payload)
    data = doc["data"]
    sv = data["shap_values"]
    if sv and not isinstance(sv[0], (list, tuple)):
        sv = [sv]
    out = {
        "shap_values": [np.asarray(v, dtype=np.float32) for v in sv],
        "expected_value": np.atleast_1d(
            np.asarray(data["expected_value"], dtype=np.float32)),
        "raw_prediction": np.atleast_2d(np.asarray(
            data["raw"]["raw_prediction"], dtype=np.float32)),
    }
    iv = data.get("raw", {}).get("interaction_values")
    if iv is not None:
        out["interaction_values"] = [np.asarray(v, dtype=np.float32)
                                     for v in iv]
    return out


# --------------------------------------------------------------------- #
# HTTP content negotiation


def is_wire_content_type(content_type: Optional[str]) -> bool:
    """Whether a ``Content-Type`` header declares a binary body (media
    type match; parameters like charset are ignored)."""

    if not content_type:
        return False
    return content_type.split(";", 1)[0].strip().lower() == CONTENT_TYPE


def accepts_wire(accept: Optional[str]) -> bool:
    """Whether an ``Accept`` header asks for a binary response.  Only an
    EXPLICIT ``application/x-dks-wire`` entry counts — ``*/*`` (and no
    header at all) keeps the historical JSON, so old clients that send a
    wildcard Accept never get bytes they cannot parse."""

    if not accept:
        return False
    for part in accept.split(","):
        if part.split(";", 1)[0].strip().lower() == CONTENT_TYPE:
            return True
    return False


# --------------------------------------------------------------------- #
# anytime streaming: versioned round frames (ISSUE 16)
#
# A streaming /explain response is a sequence of self-delimiting frames,
# one per refinement round, each wrapping a complete v1 DKSW message::
#
#     stream  := frame+
#     frame   := magic(4s="DKSS") version(u16) flags(u16) length(u32)
#                payload(length bytes, a DKSW message)
#
# flags bit 0 (:data:`STREAM_FLAG_FINAL`) marks the last frame; exactly
# one frame per stream sets it.  The payload is a standard explanation
# message (encode_explanation arrays) plus three anytime fields:
# ``round`` (i32 scalar), ``converged`` (u8 scalar), ``est_err``
# ((B, M) f32 calibrated per-feature error bars).  Reusing the DKSW
# framing inside the envelope keeps one array codec: a client that can
# read responses can read frames.
#
# Negotiation: clients ask with ``Accept: application/x-dks-wire-stream,
# application/x-dks-wire``.  A pre-anytime server matches only the plain
# wire entry and answers one ordinary binary response — the graceful
# downgrade the client is built for — while an anytime server answers
# ``Content-Type: application/x-dks-wire-stream`` with chunked frames.
# ``accepts_wire`` deliberately does NOT match the stream media type, so
# the two capabilities negotiate independently.

#: media type of a streamed (multi-frame) response
STREAM_CONTENT_TYPE = "application/x-dks-wire-stream"
#: stream envelope version (independent of :data:`WIRE_VERSION`)
STREAM_VERSION = 1
#: flags bit marking the final frame of a stream
STREAM_FLAG_FINAL = 0x1

_STREAM_MAGIC = b"DKSS"
_STREAM_HEADER = struct.Struct("<4sHHI")  # magic, version, flags, length
#: cap on a single frame payload (64 MiB) — a garbled length field must
#: not drive a multi-gigabyte allocation before the magic check fails
_MAX_FRAME_BYTES = 64 << 20


def accepts_stream(accept: Optional[str]) -> bool:
    """Whether an ``Accept`` header asks for a streamed response (explicit
    ``application/x-dks-wire-stream`` entry only, same rules as
    :func:`accepts_wire`)."""

    if not accept:
        return False
    for part in accept.split(","):
        if part.split(";", 1)[0].strip().lower() == STREAM_CONTENT_TYPE:
            return True
    return False


def encode_round_frame(shap_values, expected_value, raw_prediction,
                       round_index: int, est_err, *,
                       final: bool = False) -> bytes:
    """One stream frame for refinement round ``round_index``: a full
    explanation payload (every frame is independently usable — a client
    that stops listening keeps the best answer it saw) plus the anytime
    fields.  ``final=True`` sets :data:`STREAM_FLAG_FINAL`."""

    payload = bytearray(encode_explanation(shap_values, expected_value,
                                           raw_prediction))
    # append the anytime fields as extra arrays in the same DKSW message:
    # splice by rewriting n_arrays in the header, then extending the body
    extra = {
        "round": np.asarray([round_index], dtype=np.int32),
        "converged": np.asarray([1 if final else 0], dtype=np.uint8),
        "est_err": np.atleast_2d(np.asarray(est_err, dtype=np.float32)),
    }
    magic, version, n_arrays = _HEADER.unpack_from(payload, 0)
    tail = encode_arrays(extra)
    payload[:_HEADER.size] = _HEADER.pack(magic, version,
                                          n_arrays + len(extra))
    payload.extend(tail[_HEADER.size:])
    flags = STREAM_FLAG_FINAL if final else 0
    return _STREAM_HEADER.pack(_STREAM_MAGIC, STREAM_VERSION, flags,
                               len(payload)) + bytes(payload)


#: bytes an incremental reader must fetch before it can size a frame
STREAM_HEADER_SIZE = _STREAM_HEADER.size


def stream_frame_length(header: bytes) -> int:
    """Payload length declared by one frame's envelope header — the
    incremental reader's contract (read :data:`STREAM_HEADER_SIZE` bytes,
    call this, read exactly that many more).  Validates magic/version/cap
    with the same errors as :func:`decode_round_frame`, so a torn or
    future-version stream fails at the first header, before any payload
    bytes are waited for."""

    if len(header) < _STREAM_HEADER.size:
        raise WireError(
            f"truncated stream frame header: {len(header)} bytes "
            f"(need {_STREAM_HEADER.size})")
    magic, version, _flags, length = _STREAM_HEADER.unpack_from(header, 0)
    if magic != _STREAM_MAGIC:
        raise WireError(f"bad stream magic {bytes(magic)!r} "
                        f"(expected {_STREAM_MAGIC!r})")
    if version != STREAM_VERSION:
        raise WireVersionError(
            f"stream version {version} not supported "
            f"(this build speaks v{STREAM_VERSION})")
    if length > _MAX_FRAME_BYTES:
        raise WireError(f"stream frame declares {length} payload bytes "
                        f"(cap: {_MAX_FRAME_BYTES})")
    return int(length)


def decode_round_frame(buf, offset: int = 0):
    """Decode one frame at ``offset``.  Returns ``(frame_dict,
    next_offset)`` where ``frame_dict`` is the :func:`decode_explanation`
    structure plus ``round`` (int), ``converged`` (bool), ``est_err``
    ((B, M) f32) and ``final`` (envelope flag).  Raises
    :class:`WireError` on torn/truncated frames, :class:`WireVersionError`
    on an unknown envelope version — exactly the response-body error
    contract, so a half-written frame can never surface as phi."""

    view = memoryview(buf)
    if offset + _STREAM_HEADER.size > len(view):
        raise WireError(
            f"truncated stream frame header: {len(view) - offset} bytes "
            f"(need {_STREAM_HEADER.size})")
    magic, version, flags, length = _STREAM_HEADER.unpack_from(view, offset)
    if magic != _STREAM_MAGIC:
        raise WireError(f"bad stream magic {bytes(magic)!r} "
                        f"(expected {_STREAM_MAGIC!r})")
    if version != STREAM_VERSION:
        raise WireVersionError(
            f"stream version {version} not supported "
            f"(this build speaks v{STREAM_VERSION})")
    if length > _MAX_FRAME_BYTES:
        raise WireError(f"stream frame declares {length} payload bytes "
                        f"(cap: {_MAX_FRAME_BYTES})")
    start = offset + _STREAM_HEADER.size
    if start + length > len(view):
        raise WireError(
            f"torn stream frame: payload needs {length} bytes, "
            f"{len(view) - start} remain")
    arrays = decode_arrays(view[start:start + length])
    for key in ("shap_values", "expected_value", "raw_prediction",
                "round", "est_err"):
        if key not in arrays:
            raise WireError(f"stream frame carries no {key!r} field")
    frame = {
        "shap_values": [np.asarray(v) for v in arrays["shap_values"]],
        "expected_value": np.asarray(arrays["expected_value"]),
        "raw_prediction": np.asarray(arrays["raw_prediction"]),
        "round": int(np.asarray(arrays["round"]).reshape(-1)[0]),
        "converged": bool(np.asarray(
            arrays.get("converged", [0])).reshape(-1)[0]),
        "est_err": np.atleast_2d(np.asarray(arrays["est_err"],
                                            dtype=np.float32)),
        "final": bool(flags & STREAM_FLAG_FINAL),
    }
    return frame, start + length


def decode_round_frames(buf) -> List[Dict]:
    """Decode a complete stream body into its frames (in order).  Raises
    :class:`WireError` if the body ends mid-frame, carries trailing bytes,
    holds no frames at all, or its last frame is not marked final — a
    truncated stream must be indistinguishable from a corrupt one."""

    frames: List[Dict] = []
    offset = 0
    view = memoryview(buf)
    while offset < len(view):
        frame, offset = decode_round_frame(view, offset)
        frames.append(frame)
    if not frames:
        raise WireError("stream body holds no frames")
    if not frames[-1]["final"]:
        raise WireError("stream ended without a final frame")
    return frames
