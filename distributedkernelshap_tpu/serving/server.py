"""HTTP explanation service with request micro-batching.

TPU-native replacement for Ray Serve's replica/router machinery
(``benchmarks/serve_explanations.py:42-67``: ``serve.init`` + HTTP proxy on
port 8000, ``create_backend`` with ``num_replicas``/``max_batch_size``,
``create_endpoint`` routing ``/explain``).  There is no controller process
and no replica fleet: one server owns the compiled explain function, and a
micro-batcher coalesces concurrent requests (up to ``max_batch_size`` within
``batch_timeout_s``) into a single device call — the role Ray Serve's
``@serve.accept_batch`` played (``wrappers.py:65``), but with the batch
actually exploiting the hardware.

Implementation is stdlib-only (ThreadingHTTPServer + queue): the explain
engine serialises device work anyway, so the natural architecture is one
dispatcher thread feeding the device and N cheap HTTP threads parking on
response events.

Request flow since the scheduling subsystem landed
(``distributedkernelshap_tpu/scheduling/``):

1. the handler parses the priority class (``X-DKS-Priority``) and optional
   deadline (``X-DKS-Deadline-Ms``), answers duplicates straight from the
   result cache, and runs admission control — an over-capacity request is
   shed NOW with 429 + ``Retry-After`` instead of timing out later;
2. admitted requests enter the SLO scheduler (EDF heap, condition-variable
   wakeups), which forms row-budget-packed batches;
3. at dispatch, rows that became cached while queued are answered without
   device work and identical in-batch duplicates collapse onto one
   computation (per-batch partial-hit splitting);
4. completed payloads populate the cache and feed the service-rate
   estimator that admission's projected-wait shedding uses.
"""

import json
import logging
import math
import os
import queue
import threading
import time
import urllib.parse
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

import distributedkernelshap_tpu.observability.tracing as _tracing
import distributedkernelshap_tpu.serving.wire as _wire
from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.costmeter import (
    CostMeter,
    dispatch_shares,
)
from distributedkernelshap_tpu.observability.contprof import (
    contprof,
    register_thread_role,
)
from distributedkernelshap_tpu.observability.flightrec import flightrec
from distributedkernelshap_tpu.observability.memledger import memledger
from distributedkernelshap_tpu.observability.metrics import (
    DEFAULT_EXEMPLAR_SLOTS,
    MetricsRegistry,
)
from distributedkernelshap_tpu.observability.quality import QualityMonitor
from distributedkernelshap_tpu.observability.slo import default_server_slos
from distributedkernelshap_tpu.observability.statusz import (
    HealthEngine,
    statusz_response,
)
from distributedkernelshap_tpu.profiling import profiler
from distributedkernelshap_tpu.scheduling import (
    PRIORITY_CLASSES,
    AdmissionController,
    ResultCache,
    ServiceRateEstimator,
    StagingBuffer,
    make_scheduler,
    model_fingerprint,
    request_cache_key,
)

logger = logging.getLogger(__name__)

# Prometheus histogram bucket bounds for request latency (seconds).  Bounded
# and few: the renderer emits one line per bucket on every scrape.
LATENCY_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _HTTPServer(ThreadingHTTPServer):
    # the reference clients fan out thousands of concurrent single-row
    # requests (serve_explanations.py:131-134); the default listen backlog of
    # 5 resets connections under that load
    request_queue_size = 1024
    daemon_threads = True


class _Pending:
    __slots__ = ("array", "event", "response", "error", "t_enqueued", "done",
                 "klass", "deadline", "cache_key", "status_code", "cache_hit",
                 "trace", "wire_format", "model", "group_key", "budget",
                 "stream", "anytime_on", "anytime", "frames", "final_err")

    def __init__(self, array: np.ndarray, klass: str = "interactive",
                 deadline: Optional[float] = None,
                 cache_key: Optional[str] = None,
                 trace: Optional[_tracing.SpanContext] = None,
                 wire_format: str = "json",
                 model=None, budget: Optional[float] = None,
                 stream: bool = False, anytime_on: bool = False):
        self.array = array
        self.event = threading.Event()
        self.response: Optional[str] = None
        self.error: Optional[str] = None
        self.t_enqueued = time.monotonic()
        # set once answered; lets the watchdog fail a wedged batch while a
        # blocked finalize may still complete it later — whoever is second
        # must not double-answer or double-count
        self.done = False
        # scheduling metadata: priority class, absolute monotonic deadline
        # (None = no SLO declared), content-address for the result cache
        self.klass = klass
        self.deadline = deadline
        self.cache_key = cache_key
        # HTTP status the handler should use when ``error`` is set (the
        # watchdog/finalize failures keep the historical 500; deadline
        # expiry answers 504)
        self.status_code = 500
        # answered from cache (handler fast path, dispatch recheck, or
        # in-batch dedup) — drives the hit/miss counters
        self.cache_hit = False
        # the request's server-side root span context (None when tracing
        # is off); the dispatcher/finalizer threads parent queue-wait /
        # device / finalize spans to it
        self.trace = trace
        # negotiated response encoding: "json" (historical Explanation
        # document) or "binary" (serving/wire.py raw-bytes payload, asked
        # for via Accept and only granted when the model can produce it)
        self.wire_format = wire_format
        # registry mode: the RegisteredModel PINNED at admission — a
        # hot-swap mid-flight must not change this request's answer, so
        # dispatch/caching/metrics all read the pinned version (None in
        # single-model mode)
        self.model = model
        # memoised dispatch-group identity (server._group_key_for):
        # computed once per request by the grouping policy's first
        # sighting — share-peer lookups take the registry lock, and the
        # scheduler calls key() inside its own critical section
        self.group_key = None
        # anytime refinement (ISSUE 16): error budget from the
        # X-DKS-Error-Budget header (None = none declared), whether the
        # client negotiated streamed round frames, and whether this
        # request refines progressively at all.  ``anytime`` holds the
        # engine's AnytimeRun between rounds (the preempted state the
        # scheduler requeues); ``frames`` the handler-facing stream
        # queue; ``final_err`` the reported error of the answer actually
        # sent (0.0 = full fidelity — the cache's keep-best key).
        self.budget = budget
        self.stream = stream
        self.anytime_on = anytime_on
        self.anytime = None
        self.frames = queue.Queue() if stream else None
        self.final_err = 0.0

    @property
    def rows(self) -> int:
        return self.array.shape[0]


def calibrate_pipeline_depth(model, example_array: Optional[np.ndarray] = None,
                             candidates=(2, 4, 8, 16, 24),
                             probes: int = 32, budget_s: float = 60.0,
                             fallback: int = 8) -> int:
    """Measure pipelined throughput at a few depths and return the best one.

    The optimal number of in-flight device batches is environment-dependent:
    through a tunnelled TPU every D2H fetch is a ~70 ms RPC and fetches
    overlap only across threads, so small-batch throughput keeps climbing to
    depth ~16, while a locally attached chip plateaus almost immediately —
    round 1's hand-set depths spanned a 3.7x wall-clock spread.  This short
    self-calibration replaces the hand tuning: for each candidate depth it
    pushes ``probes`` batches through the same bounded dispatch/finalize
    pipeline the server runs (finalize threads capped like the server's
    finalizer count) and keeps the depth with the best measured throughput;
    a larger depth must win by >5% so ties resolve to fewer in-flight
    buffers.

    The whole measurement is bounded by ``budget_s``: a wedged/hung device
    (or a model whose finalize raises) must not block server startup, so
    calibration runs on daemon threads and ``fallback`` is returned — with
    a warning — if it has not completed in time.
    """

    if not hasattr(model, "explain_batch_async"):
        return 1
    if example_array is None:
        example_array = model.explainer._explainer.background[:1]
    row = np.atleast_2d(np.asarray(example_array, dtype=np.float32))[:1]

    out = {}
    done = threading.Event()
    cancelled = threading.Event()  # budget expiry: stop issuing new probes

    def _finish(fin, sem, fetch_gate):
        try:
            with fetch_gate:  # the server caps concurrent fetch threads at 8
                fin()
        except Exception:
            logger.debug("calibration probe failed", exc_info=True)
        finally:
            sem.release()

    def _calibrate():
        try:
            # warmup: compile + first transfer out of the timed region
            model.explain_batch_async(row, split_sizes=[1])()
            best_depth, best_tp = 1, -1.0
            for depth in candidates:
                sem = threading.BoundedSemaphore(depth)  # in-flight bound
                fetch_gate = threading.BoundedSemaphore(min(depth, 8))
                threads = []
                t0 = time.perf_counter()
                for _ in range(probes):
                    if cancelled.is_set():
                        return  # abandoned: don't contend with live traffic
                    sem.acquire()
                    fin = model.explain_batch_async(row, split_sizes=[1])
                    t = threading.Thread(target=_finish,
                                         args=(fin, sem, fetch_gate),
                                         daemon=True)
                    t.start()
                    threads.append(t)
                for t in threads:
                    t.join()
                tp = probes / (time.perf_counter() - t0)
                if tp > best_tp * 1.05:
                    best_depth, best_tp = depth, tp
            out["depth"], out["tp"] = best_depth, best_tp
        except Exception:
            logger.exception("depth calibration failed")
        finally:
            done.set()

    threading.Thread(target=_calibrate, daemon=True).start()
    if not done.wait(budget_s) or "depth" not in out:
        cancelled.set()
        logger.warning("depth calibration did not complete within %.0fs; "
                       "using pipeline_depth=%d", budget_s, fallback)
        return fallback
    logger.info("calibrated pipeline_depth=%d (%.1f req/s)",
                out["depth"], out["tp"])
    return out["depth"]


def resolve_warmup_env(default: bool) -> bool:
    """The ONE ``DKS_WARMUP`` parser (standalone servers default warmup
    off, replica workers default it on); shared warn-on-garbage contract
    in ``utils.resolve_bool_env``."""

    from distributedkernelshap_tpu.utils import resolve_bool_env

    return resolve_bool_env("DKS_WARMUP", default)


def resolve_staging_env(default: bool) -> bool:
    """The ONE ``DKS_STAGING`` parser (same contract as
    :func:`resolve_warmup_env`)."""

    from distributedkernelshap_tpu.utils import resolve_bool_env

    return resolve_bool_env("DKS_STAGING", default)


def resolve_shared_batch_env(default: bool) -> bool:
    """The ONE ``DKS_SHARED_BATCH`` parser (same contract as
    :func:`resolve_warmup_env`).  ``DKS_SHARED_BATCH=0`` is the
    cross-tenant-batching escape hatch: registry-mode batch formation
    reverts to the PR-10 tenant-blind EDF pop + per-(model, version)
    group split, with no shared-program coalescing."""

    from distributedkernelshap_tpu.utils import resolve_bool_env

    return resolve_bool_env("DKS_SHARED_BATCH", default)


def resolve_cost_meter_env(default: bool) -> bool:
    """The ONE ``DKS_COST_METER`` parser (same contract as
    :func:`resolve_warmup_env`).  ``DKS_COST_METER=0`` disables the
    per-tenant device-time meter's write path (the metric families
    still register, frozen at zero) — the cost-attribution bench's
    control arm for its ≤1% overhead criterion."""

    from distributedkernelshap_tpu.utils import resolve_bool_env

    return resolve_bool_env("DKS_COST_METER", default)


class _TenantGrouping:
    """Adapter between the server's tenant facts and the scheduler's
    grouped batch formation (``SLOScheduler._fill_grouped``): ``key`` maps
    a pending request to its dispatch-group identity (shared-program key
    when eligible, else the stable ``(model_id, version)``), ``bucket``
    exposes the group engine's compile-bucket ladder so packing can fill
    a tenant's sub-batch to a bucket boundary, and ``limit`` surfaces the
    tenant's in-flight quota bound as a per-cycle cap (a tenant at its
    bound yields its slots instead of fragmenting the cycle)."""

    _MAX_META = 128  # group keys remembered (rm + bucket fn); LRU

    def __init__(self, server):
        self._server = server
        # key -> (rm, bucket_fn_or_None); true LRU (move_to_end on every
        # sighting) so version churn evicts IDLE keys, never the busiest
        # tenants' — a FIFO-by-first-sighting bound would thrash exactly
        # the longest-registered, highest-traffic groups
        self._meta: "OrderedDict[object, tuple]" = OrderedDict()

    def _remember(self, key, rm) -> None:
        # REFRESHED on every sighting, not first-seen: the cached rm
        # drives limit(), and a share key survives a content-identical
        # hot swap — a quota tightened at swap time must bite the very
        # next cycle, and a retired version must not linger here
        prev = self._meta.get(key)
        if prev is not None and prev[0] is rm:
            self._meta.move_to_end(key)
            return
        bucket = (self._server._bucket_fn(rm.model)
                  if rm.model is not None else None)
        self._meta[key] = (rm, bucket)
        self._meta.move_to_end(key)
        while len(self._meta) > self._MAX_META:
            self._meta.popitem(last=False)

    def key(self, item):
        rm = getattr(item, "model", None)
        if rm is None:
            return None
        # memoised per request: the share-peer lookup takes the registry
        # lock and this runs per scanned candidate inside the
        # scheduler's critical section
        k = getattr(item, "group_key", None)
        if k is None:
            k = self._server._group_key_for(rm)
            try:
                item.group_key = k
            except AttributeError:
                pass  # foreign item types just recompute next time
        self._remember(k, rm)
        return k

    def bucket(self, key, rows: int) -> int:
        meta = self._meta.get(key)
        if meta is None or meta[1] is None:
            return rows
        return int(meta[1](rows))

    def limit(self, key):
        # shared-program groups span tenants, so no single tenant's
        # in-flight bound may cap the GROUP (each tenant's own bound is
        # already enforced at admission — its queued requests can never
        # exceed it — and throttling tenant B by tenant A's quota would
        # be arbitrary cross-tenant interference)
        if not isinstance(key, tuple) or key[0] != "model":
            return None
        meta = self._meta.get(key)
        quota = getattr(meta[0], "quota", None) if meta is not None else None
        bound = getattr(quota, "max_inflight", None)
        return int(bound) if bound else None


class ExplainerServer:
    """Serves a fitted serving model over HTTP on ``/explain``.

    Parameters
    ----------
    model
        A ``KernelShapModel``-like object exposing ``explain_batch``.
    host, port
        Bind address (reference default: Serve HTTP proxy on 8000,
        ``cluster/ray_cluster.yaml:33-35``).
    max_batch_size
        Maximum requests coalesced into one device call (the reference's
        ``serve.update_backend_config({'max_batch_size': ...})`` knob,
        ``serve_explanations.py:65``).  1 disables batching.
    batch_timeout_s
        How long the dispatcher waits to fill a batch once a first request
        has arrived.
    pipeline_depth
        In-flight device batches (the TPU-native reading of the reference's
        replica count).  ``None`` (default) self-calibrates at ``start()``
        via :func:`calibrate_pipeline_depth`.
    watchdog_timeout_s
        Fault isolation (the reference got replica-process crash isolation
        from Ray Serve for free; one process serving one device mesh needs
        an explicit liveness story): if dispatched work makes no progress
        for this long, the watchdog fails every affected request with a
        fast error, marks the server wedged (``/explain`` answers 503,
        ``/healthz`` fails so an orchestrator restarts the pod) and drops
        the model's device-resident state so a recovered backend is not
        handed dead buffers.  A later successful batch clears the flag.
    device_probe_timeout_s
        Bound on the tiny device round trip ``/healthz`` performs — a
        wedged tunnel turns the probe into a hang, which the bound converts
        into an unhealthy verdict.
    scheduling
        Batch-formation policy: ``"slo"`` (default — EDF over priority
        classes + deadlines, ``scheduling/scheduler.py``) or ``"fifo"``
        (arrival order; the pre-scheduler behaviour, kept as the benchmark
        control arm).
    class_budgets
        Optional ``{class: seconds}`` overriding the EDF ordering budgets
        for requests with no explicit deadline.
    default_class
        Priority class assumed when a request carries no
        ``X-DKS-Priority`` header.
    max_queue_per_class
        Admission bound on queued requests per priority class (int, or a
        per-class dict; 0/None disables).  A full class answers 429 +
        ``Retry-After``.
    rate_limit_per_client
        ``(requests_per_s, burst)`` token-bucket rate limit keyed by
        ``X-DKS-Client`` (else peer address).  ``None`` (default) disables.
    cache_bytes
        Byte budget for the content-addressed explanation cache
        (``scheduling/result_cache.py``).  0 (default) disables caching.
    admission_control
        ``False`` disables every admission gate (queue bounds, rate
        limits, projected-wait shedding) — the pre-scheduler accept-
        everything behaviour, used as the benchmark control arm.
    fault_injector
        Optional :class:`~distributedkernelshap_tpu.resilience.faults.
        FaultInjector` consulted at the ``server.accept`` (post-parse,
        pre-admission) and ``server.explain`` (pre-success-reply)
        sites — the chaos harness's hook into the REAL request path.
        ``replica_worker`` wires this from the ``DKS_FAULTS`` env;
        ``None`` (the default) is zero-overhead.
    health_interval_s
        Sampling/alert-evaluation period of the SLO health engine behind
        ``/statusz`` (``observability/statusz.py``).  The sampler is one
        daemon thread snapshotting the metrics registry — nothing on the
        request path.  ``0`` disables the background thread; ``/statusz``
        still serves (cold page).
    slos, alert_rules, alert_sinks
        Override the health engine's SLO set (default
        :func:`~distributedkernelshap_tpu.observability.slo.
        default_server_slos`), alert rules (default: one burn-rate rule
        per SLO) and sinks (default: log + flight recorder).
    warmup
        Precompile **warmup ladder** (docs/PERFORMANCE.md): at start the
        dispatcher thread traces+compiles the engine over every bucket
        shape up to ``max_batch_size`` rows, so the first real request of
        any bucket lands on a warm program.  While warming, ``/healthz``
        answers 503 ``{"status": "warming", ...}`` — the fan-in prober
        will not route to the replica and an orchestrator's readiness
        gate holds — and progress renders on ``/statusz``.  ``None``
        (default) resolves from the ``DKS_WARMUP`` env (off unless
        truthy); replica workers default it ON.  A warmup failure is
        logged and serving proceeds (the first real requests then pay the
        compiles, exactly the pre-warmup behaviour).
    staging
        Double-buffered host→device staging pipeline (the zero-copy
        streaming hot path, docs/PERFORMANCE.md): batch formation +
        stacking + ``jax.device_put`` move to a dedicated batcher thread,
        so while batch *k* computes, batch *k+1*'s rows are already
        device-resident and the dispatcher never waits on an H2D copy.
        ``None`` (default) resolves from the ``DKS_STAGING`` env (off
        unless truthy).  Engages only for models exposing ``stage_rows`` +
        ``explain_batch_async`` (the serving wrappers); otherwise the
        single-thread dispatch loop runs unchanged.  Overlap is measured
        as ``dks_staging_overlap_seconds_total``.
    shared_batching
        Cross-tenant continuous batching (registry mode only;
        docs/MULTITENANCY.md): batch formation becomes tenant-aware
        (bucket-boundary packing + deficit-round-robin fairness in
        ``scheduling/scheduler.py``) and tenants whose deployments
        dispatch the IDENTICAL compiled program over IDENTICAL device
        constants (equal ``RegisteredModel.share_key``) coalesce into ONE
        device call, with per-leader ``split_sizes`` carrying the tenant
        boundaries — phi bit-identical to dedicated dispatch at the same
        padded shape.  ``None`` (default) resolves from the
        ``DKS_SHARED_BATCH`` env (ON unless falsy); ``False`` restores
        the PR-10 serialized per-model dispatch byte-identically.
        Single-model servers are unaffected either way.
    staging_depth
        Staged batches the staging buffer may hold at once, and how many
        groups AHEAD of the dispatcher the batcher runs their
        host→device uploads (so in-flight staged device buffers stay
        bounded by roughly twice this knob).  ``None`` (default): 1 in
        single-model mode (the classic double buffer), else the
        active-tenant count capped at 4 — a cycle's tenant groups upload
        while earlier groups compute, instead of the batcher blocking
        after staging one group.
    cost_metering
        Per-tenant device-time metering + tenant cost counters
        (``observability/costmeter.py``; docs/OBSERVABILITY.md "Cost
        attribution & fleet view"): every dispatched device call is
        bracketed dispatch→fetch on the monotonic clock (compile time
        excluded via the compile accountant) and prorated across the
        batch's tenants by row share into
        ``dks_device_seconds_total{model,version,path}``, alongside
        per-tenant rows / wire bytes / shed / cache-hit / latency
        accounting.  ``None`` (default) resolves from ``DKS_COST_METER``
        (ON unless falsy); ``False`` freezes the families at zero with
        no write-path bookkeeping.  Single-model servers attribute to
        ``model="default"``.
    """

    def __init__(self, model=None, host: str = "0.0.0.0", port: int = 8000,
                 max_batch_size: int = 1, batch_timeout_s: float = 0.01,
                 pipeline_depth: Optional[int] = None,
                 watchdog_timeout_s: float = 120.0,
                 first_batch_grace_s: float = 600.0,
                 device_probe_timeout_s: float = 5.0,
                 scheduling: str = "slo",
                 class_budgets: Optional[dict] = None,
                 default_class: str = "interactive",
                 max_queue_per_class=4096,
                 rate_limit_per_client: Optional[Tuple[float, float]] = None,
                 cache_bytes: int = 0,
                 admission_control: bool = True,
                 fault_injector=None,
                 health_interval_s: float = 1.0,
                 slos=None, alert_rules=None, alert_sinks=None,
                 warmup: Optional[bool] = None,
                 staging: Optional[bool] = None,
                 shared_batching: Optional[bool] = None,
                 staging_depth: Optional[int] = None,
                 cost_metering: Optional[bool] = None,
                 registry=None):
        # multi-tenant gateway mode (registry/registry.py): requests route
        # by X-DKS-Model (or the JSON/wire `model` field) to the named
        # tenant's ACTIVE version; ``model`` then only names the default
        # deployment used for depth calibration and staging capability
        # resolution (None = the registry's default model at start()).
        # Without a registry the server is the historical single-model one.
        if model is None and registry is None:
            raise ValueError("ExplainerServer needs a model, a registry, "
                             "or both")
        self._registry = registry
        if registry is not None:
            registry.attach_server(self)
        self.model = model
        self.host = host
        self.port = port
        self.max_batch_size = max(1, int(max_batch_size))
        self.batch_timeout_s = batch_timeout_s
        self.pipeline_depth = (None if pipeline_depth is None
                               else max(1, int(pipeline_depth)))
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        # a server that has never completed a batch may legitimately be
        # inside its first jit compile (~40-140 s on a tunnelled chip, and
        # serve_multihost skips the calibration warm-up that would absorb
        # it) — the watchdog must not declare that a wedge
        self.first_batch_grace_s = max(float(first_batch_grace_s),
                                       self.watchdog_timeout_s)
        self.device_probe_timeout_s = float(device_probe_timeout_s)
        # dispatched-but-unanswered batches, keyed by id(batch): the
        # watchdog's view of what a wedged device call is holding hostage
        self._active = {}
        self._active_lock = lockwitness.make_lock("server.active")
        self._last_progress = time.monotonic()
        self._ever_completed = False
        self._wedged = threading.Event()
        # at most one outstanding health probe thread: while the device is
        # wedged the probe thread is stuck inside an XLA call
        # (uncancellable) — concurrent health checks JOIN the in-flight
        # probe instead of stacking threads
        self._probe_lock = lockwitness.make_lock("server.probe")
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_done: Optional[threading.Event] = None
        self._probe_started = 0.0
        # the claim lock: pending.done transitions (watchdog-vs-finalize
        # races) and their counter updates happen under it, so a request
        # can never be double-answered or double-counted.  The counters
        # themselves live in the shared observability registry (each
        # metric has its own lock; nesting is safe because registry locks
        # never acquire this one).
        self._metrics_lock = lockwitness.make_lock("server.requests")
        # scheduling subsystem: EDF (or FIFO-baseline) request queue,
        # admission control fed by an EWMA of observed device throughput,
        # optional content-addressed result cache
        if default_class not in PRIORITY_CLASSES:
            raise ValueError(f"default_class must be one of "
                             f"{PRIORITY_CLASSES}, got {default_class!r}")
        self.default_class = default_class
        self._sched = make_scheduler(scheduling, class_budgets=class_budgets)
        self._service_rate = ServiceRateEstimator()
        self._admission = (AdmissionController(
            max_queued_per_class=max_queue_per_class,
            rate_limit_per_client=rate_limit_per_client,
            estimator=self._service_rate) if admission_control else None)
        # the result cache charges its byte budget into the process-wide
        # device-memory ledger (observability/memledger.py) so /statusz
        # and dks_device_bytes{owner="result_cache"} see it; under
        # DKS_MEM_BUDGET_BYTES pressure the ledger evicts LRU entries
        # through evict_bytes — answers recompute bit-identically
        self._cache = (ResultCache(
            cache_bytes,
            mem_account=memledger().account("result_cache"))
            if cache_bytes else None)
        if self._cache is not None:
            memledger().register_pressure_callback(self._cache.evict_bytes)
        self._faults = fault_injector
        # precompile warmup ladder (see the ``warmup`` parameter): state is
        # read by /healthz, /statusz and the dks_serve_warming metrics;
        # mutated only by the dispatcher thread under the lock
        if warmup is None:
            warmup = resolve_warmup_env(default=False)
        self._warmup_lock = lockwitness.make_lock("server.warmup")
        self._warmup_state = {
            "enabled": bool(warmup),
            "state": "pending" if warmup else "off",
            "buckets": [], "completed_buckets": [], "current": None,
            "elapsed_s": 0.0, "error": None, "compile": {},
        }
        # observability: every dks_serve_* series is registered here and
        # /metrics is rendered solely by the registry (one renderer for
        # the whole process — SURVEY.md §5.5; docs/OBSERVABILITY.md holds
        # the catalog).  Per-instance, not global: tests run several
        # servers per process.
        self.metrics = MetricsRegistry()
        self._flight = flightrec()
        self._tracer = _tracing.tracer()
        # tenant cost-attribution plane (observability/costmeter.py):
        # device-seconds per (model, version, path) + tenant counters,
        # registered with everything else so the catalog is
        # mode-independent; DKS_COST_METER=0 freezes the write path
        if cost_metering is None:
            cost_metering = resolve_cost_meter_env(default=True)
        self._costmeter = CostMeter(enabled=bool(cost_metering))
        # continuous correctness plane (observability/quality.py):
        # in-band invariant auditor on every finalized answer, budgeted
        # shadow-oracle sampler billed to the ``_quality`` tenant, and
        # the hot-swap canary drift sentinel the registry consults.
        # Per-instance like the cost meter (tests and the obs-check live
        # catalog run several servers per process); the background
        # drain/canary thread starts with the server in start().
        self._quality = QualityMonitor(server=self,
                                       costmeter=self._costmeter)
        self._register_metrics()
        # SLO health engine (observability/statusz.py): samples the
        # registry into a bounded time-series store, evaluates burn-rate
        # SLOs + alert rules on the same tick, serves /statusz.  Built in
        # __init__ (not start()) so the dks_slo_*/dks_alerts_* series
        # register alongside the rest and obs-check sees them.
        # With the default SLO set (slos=None) a registry-mode server
        # additionally templates per-tenant latency/availability
        # objectives for the current roster and REFRESHES them on
        # registration/removal (_refresh_tenant_slos) — an explicit
        # slos= override opts out of both.
        self._auto_slos = slos is None
        if slos is None:
            slos = default_server_slos(
                tenants=registry.model_ids() if registry is not None
                else ())
        self.health = HealthEngine(
            self.metrics, component="server",
            slos=slos,
            rules=alert_rules, sinks=alert_sinks, flight=self._flight,
            interval_s=health_interval_s,
            spark_names=("dks_serve_requests_total",
                         "dks_serve_errors_total",
                         "dks_serve_queue_depth",
                         "dks_serve_sheds_total"))
        # computed lazily on first request: fingerprinting hashes the
        # background data, and the model may be swapped between __init__
        # and start() in tests.  Staleness is detected by OBJECT IDENTITY:
        # to change the served model with caching enabled, REPLACE
        # ``self.model`` with a new object (or pin ``model.fingerprint``)
        # — mutating the current model in place (in-place refit, swapping
        # its predictor) is not detected, and re-hashing the background on
        # every request to detect it would cost more than the cache saves.
        # The pinned object also transitively keeps its predictor alive,
        # so id(predictor) inside model_fingerprint cannot alias a new
        # object at a recycled address while the fingerprint is cached.
        self._model_fp: Optional[str] = None
        self._model_fp_model = None
        self._model_fp_lock = lockwitness.make_lock("server.model_fp")
        self._last_complete_t = time.monotonic()
        # double-buffered host→device staging (see the ``staging``
        # parameter): requested here, resolved against the model's
        # capabilities in start(); the buffer exists only when active
        if staging is None:
            staging = resolve_staging_env(default=False)
        self._staging_requested = bool(staging)
        self._staging_enabled = False
        self._staged: Optional[StagingBuffer] = None
        self.staging_depth = (None if staging_depth is None
                              else max(1, int(staging_depth)))
        # cross-tenant continuous batching (registry mode only; see the
        # ``shared_batching`` parameter): tenant-aware packing in the
        # scheduler + shared-program coalescing in _form_batch
        if shared_batching is None:
            shared_batching = resolve_shared_batch_env(default=True)
        self._shared_batching = bool(shared_batching)
        self._grouping = _TenantGrouping(self)
        # (batch, finalize) pairs already dispatched to the device; bounded so
        # a slow host can't pile up unbounded in-flight device work (the
        # queue is created in start(), once the depth is known)
        self._inflight: "queue.Queue" = None
        self._stop = threading.Event()
        self._dispatch_done = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads = []

    # ------------------------------------------------------------------ #

    def _register_metrics(self) -> None:
        """Declare every dks_serve_* series on the shared registry.  The
        names, label sets and HELP strings are byte-compatible with the
        pre-registry hand-rolled renderer; render order is registration
        order."""

        reg = self.metrics
        self._m_requests = reg.counter(
            "dks_serve_requests_total", "Requests answered.")
        self._m_errors = reg.counter(
            "dks_serve_errors_total", "Requests answered with an error.")
        self._m_rows = reg.counter(
            "dks_serve_rows_total", "Instance rows explained.")
        self._m_batches = reg.counter(
            "dks_serve_batches_total", "Coalesced device batches.")
        self._m_request_seconds = reg.counter(
            "dks_serve_request_seconds_sum", "Total queue+explain time.")
        reg.gauge("dks_serve_pipeline_depth",
                  "In-flight device batches.").set_function(
            lambda: self.pipeline_depth or 0)
        self._m_wedges = reg.counter(
            "dks_serve_wedges_total", "Watchdog-declared device wedges.")
        reg.gauge("dks_serve_wedged",
                  "Whether the server is currently wedged.").set_function(
            lambda: int(self._wedged.is_set()))
        reg.gauge("dks_serve_queue_depth",
                  "Queued requests by priority class.",
                  labelnames=("class",)).set_function(
            lambda: {(k,): v
                     for k, v in sorted(self._sched.depths().items())})
        # the three admission reasons are refused before entering the
        # pipeline and do NOT appear in requests_total; deadline_expired
        # requests were admitted and answered (504), so they count in BOTH
        # requests_total/errors_total and here — don't compute goodput as
        # requests_total - sheds_total
        self._m_sheds = reg.counter(
            "dks_serve_sheds_total",
            "Requests shed before dispatch, by reason.",
            labelnames=("reason",)).seed(
            "deadline_expired", "projected_wait", "queue_full",
            "rate_limited", "tenant_queue_full", "tenant_rate_limited")
        # streaming hot path: payload bytes by negotiated wire format
        # (rx = request bodies, tx = success response payloads) and the
        # measured upload/compute overlap of the staging pipeline
        self._m_wire_bytes = reg.counter(
            "dks_wire_bytes_total",
            "Payload bytes on /explain by wire format and direction "
            "(rx = request bodies, tx = success responses).",
            labelnames=("format", "direction")).seed(
            ("binary", "rx"), ("binary", "tx"),
            ("json", "rx"), ("json", "tx"))
        # anytime refinement (ISSUE 16): rounds dispatched, stop-reason
        # accounting (the three legs of the stop rule), frames streamed,
        # and the reported error of answers actually sent — the
        # error-budget SLO (observability/slo.py anytime_error_slo)
        # burns against the histogram
        self._m_anytime_rounds = reg.counter(
            "dks_anytime_rounds_total",
            "Refinement rounds dispatched to the device (each is one "
            "accumulated-WLS device call; a request spans >=1).")
        self._m_anytime_refines = reg.counter(
            "dks_anytime_refines_total",
            "Anytime requests answered, by stop reason (budget_met = "
            "reported error under the declared X-DKS-Error-Budget; "
            "deadline = next round would overrun X-DKS-Deadline-Ms; "
            "exhausted = full nsamples schedule ran).",
            labelnames=("reason",)).seed(
            "budget_met", "deadline", "exhausted")
        self._m_anytime_final_err = reg.histogram(
            "dks_anytime_final_err",
            "Reported (calibrated) max per-feature error of anytime "
            "answers actually sent.",
            buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1, 3e-1, 1.0),
            exemplar_slots=DEFAULT_EXEMPLAR_SLOTS)
        self._m_anytime_stream_frames = reg.counter(
            "dks_anytime_stream_frames_total",
            "Partial-result DKSS frames written to streaming clients.")
        self._m_staging_overlap = reg.counter(
            "dks_staging_overlap_seconds_total",
            "Seconds staged batches sat device-ready before dispatch "
            "(host-to-device upload overlapped with the previous batch's "
            "compute).")
        # cross-tenant batching density: device groups per scheduler
        # cycle (1 = fully coalesced; tenant-count = fully serialized)
        # and the bucket-padding rows each dispatch actually paid — the
        # waste the tenant-aware packer + shared programs remove
        self._m_batch_groups = reg.histogram(
            "dks_serve_batch_groups",
            "Per-model device groups formed per scheduler cycle "
            "(multi-tenant dispatch density; 1 = fully coalesced).",
            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
        self._m_padded_rows = reg.counter(
            "dks_serve_padded_rows_total",
            "Bucket-padding rows dispatched to the device per model "
            "(rows the engine padded on top of real request rows).",
            labelnames=("model",))
        # model-labeled: retired by ModelRegistry.unregister (the
        # obs-check cardinality lint's retire-hook declaration)
        reg.declare_retirement("dks_serve_padded_rows_total")
        # latency histograms carry trace exemplars (last-K per bucket):
        # an SLO breach on /statusz links to the trace ids that landed
        # in the slow buckets (/debugz "exemplars")
        self._m_latency = reg.histogram(
            "dks_serve_request_latency_seconds",
            "Queue+explain latency of answered requests.",
            buckets=LATENCY_BUCKETS_S,
            exemplar_slots=DEFAULT_EXEMPLAR_SLOTS)
        # per-priority-class latency: the input the per-class latency
        # SLOs (observability/slo.py CLASS_LATENCY_TARGETS) burn against.
        # A separate family — adding a label to the unlabeled histogram
        # above would break every dashboard scraping it.
        self._m_class_latency = reg.histogram(
            "dks_serve_class_latency_seconds",
            "Queue+explain latency of answered requests by priority "
            "class.",
            buckets=LATENCY_BUCKETS_S, labelnames=("class",),
            exemplar_slots=DEFAULT_EXEMPLAR_SLOTS)
        # tenant cost attribution (observability/costmeter.py):
        # dks_device_seconds_total + the dks_tenant_* families
        self._costmeter.attach_metrics(reg)
        # trace-sink rotation accounting (observability/tracing.py):
        # spans this process deleted from its DKS_TRACE_DIR sink
        reg.counter(
            "dks_trace_dropped_total",
            "Spans deleted from this process's DKS_TRACE_DIR JSONL sink "
            "by size/age rotation (one rotated generation is kept; "
            "older ones drop with their spans).").set_function(
            lambda: float(self._tracer.sink_dropped_total))
        # the watchdog's progress view, made continuous for the staleness
        # SLO: seconds since dispatched work last progressed, 0 when idle
        # (an idle server is not stalling)
        def _stall_age():
            with self._active_lock:
                busy = bool(self._active)
                last = self._last_progress
            return (time.monotonic() - last) if busy else 0.0

        reg.gauge("dks_serve_last_progress_age_seconds",
                  "Seconds since in-flight device work last progressed "
                  "(0 when nothing is dispatched).").set_function(
            _stall_age)
        if self._cache is not None:
            self._m_cache_hits = reg.counter(
                "dks_serve_cache_hits_total",
                "Requests answered from the result cache (incl. in-batch "
                "dedup).")
            self._m_cache_misses = reg.counter(
                "dks_serve_cache_misses_total",
                "Requests that cost device work.")
            reg.gauge("dks_serve_cache_entries",
                      "Cached explanations.").set_function(
                lambda: self._cache.stats()["entries"])
            reg.gauge("dks_serve_cache_bytes",
                      "Bytes held by the result cache.").set_function(
                lambda: self._cache.stats()["bytes"])
            reg.counter("dks_serve_cache_evictions_total",
                        "LRU evictions under the byte budget.").set_function(
                lambda: self._cache.stats()["evictions"])
        # cold-start subsystem: warmup-ladder readiness state plus the
        # process-global compile accounting (runtime/compile_cache.py) —
        # fresh-vs-persistent-cache-hit compile counts and seconds, by
        # declared shape signature
        reg.gauge("dks_serve_warming",
                  "Whether the precompile warmup ladder is still gating "
                  "readiness.").set_function(lambda: int(self._warming()))
        reg.gauge("dks_serve_warmup_buckets_total",
                  "Bucket shapes in the warmup ladder.").set_function(
            lambda: len(self._warmup_state["buckets"]))
        reg.gauge("dks_serve_warmup_buckets_done",
                  "Warmup ladder buckets already compiled.").set_function(
            lambda: len(self._warmup_state["completed_buckets"]))
        from distributedkernelshap_tpu.runtime.compile_cache import (
            compile_events,
        )

        compile_events().attach_metrics(reg)
        # evaluation-path attribution (exact closed-form TreeSHAP vs the
        # sampled estimator) and the analytic paths' fallback accounting —
        # all process-global, rendered via callbacks like the compile
        # accountant
        from distributedkernelshap_tpu.attribution.deepshap import (
            attach_deepshap_metrics,
        )
        from distributedkernelshap_tpu.ops.tensor_shap import (
            attach_tensor_shap_metrics,
        )
        from distributedkernelshap_tpu.ops.treeshap import (
            attach_treeshap_metrics,
        )
        from distributedkernelshap_tpu.serving.wrappers import (
            attach_path_metrics,
        )

        attach_path_metrics(reg)
        attach_treeshap_metrics(reg)
        attach_tensor_shap_metrics(reg)
        attach_deepshap_metrics(reg)
        # pod broadcast metering (serving/multihost.py): process-global
        # like the fallback accountants — zero series until a pod model
        # actually broadcasts, but always registered so the catalog is
        # mode-independent
        from distributedkernelshap_tpu.serving.multihost import (
            attach_pod_metrics,
        )

        attach_pod_metrics(reg)
        # the scheduler registers its own dks_sched_* series (queue wait,
        # expiries) on the same registry so one page carries everything
        attach = getattr(self._sched, "attach_metrics", None)
        if attach is not None:
            attach(reg)
        # device-phase time from the per-process profiler, surfaced
        # without enabling full tracing (callback-sourced: the profiler
        # owns the truth, the registry renders it)
        reg.counter("dks_phase_seconds_total",
                    "Total seconds per engine profiling phase "
                    "(DKS_PROFILE=1).",
                    labelnames=("phase",)).set_function(
            lambda: {(name,): s["total_s"]
                     for name, s in profiler().summary().items()})
        reg.counter("dks_phase_count",
                    "Completed engine profiling phases (DKS_PROFILE=1).",
                    labelnames=("phase",)).set_function(
            lambda: {(name,): s["count"]
                     for name, s in profiler().summary().items()})
        # multi-tenant registry (registry/registry.py): per-model request /
        # latency / quota-shed / swap accounting, rendered via callbacks
        # into the attached registry (empty series in single-model mode —
        # the families still register so the catalog is mode-independent)
        self._register_registry_metrics(reg)
        # weak-fingerprint accounting (scheduling/result_cache.py): model
        # fingerprints that fell back to in-process identity — the
        # stale-cache-across-restart hazard, now loud instead of silent
        from distributedkernelshap_tpu.scheduling.result_cache import (
            attach_weak_fingerprint_metric,
        )

        attach_weak_fingerprint_metric(reg)
        # continuous sampling profiler (observability/contprof.py):
        # sample/drop/overhead counters for the always-on wall-clock
        # sampler behind /profilez
        contprof().attach_metrics(reg)
        # device-memory ledger (observability/memledger.py): per-owner
        # device bytes + high-water/budget/pressure series
        memledger().attach_metrics(reg)
        # continuous correctness plane (observability/quality.py):
        # audit/violation counters, shadow-oracle error gauges and the
        # canary drift gauge behind /qualityz
        self._quality.attach_metrics(reg)

    def _register_registry_metrics(self, reg) -> None:
        def from_registry(method):
            def sample():
                r = self._registry
                return getattr(r, method)() if r is not None else {}
            return sample

        reg.gauge(
            "dks_registry_models",
            "Active (model, version) deployments by classified engine "
            "path (1 per active version).",
            labelnames=("model", "version", "path")).set_function(
            from_registry("metric_models"))
        reg.counter(
            "dks_registry_requests_total",
            "Requests answered per registered model (active versions; "
            "counted on the version that admitted the request).",
            labelnames=("model",)).set_function(
            from_registry("metric_requests"))
        reg.counter(
            "dks_registry_request_seconds_total",
            "Total queue+explain seconds per registered model.",
            labelnames=("model",)).set_function(
            from_registry("metric_seconds"))
        reg.gauge(
            "dks_registry_inflight",
            "Requests currently pinned to each registered model "
            "(queued + executing).",
            labelnames=("model",)).set_function(
            from_registry("metric_inflight"))
        reg.counter(
            "dks_registry_sheds_total",
            "Requests shed by per-tenant quotas, by model and reason "
            "(tenant_rate_limited = token bucket, tenant_queue_full = "
            "in-flight bound); these also count in dks_serve_sheds_total "
            "under the same reasons.",
            labelnames=("model", "reason")).set_function(
            from_registry("metric_sheds"))
        reg.counter(
            "dks_registry_swaps_total",
            "Version registrations per model id (the first registration "
            "counts too; value N means N-1 hot swaps).",
            labelnames=("model",)).set_function(
            from_registry("metric_swaps"))
        # all callback-sourced from the registry, whose unregister()
        # removes a tenant at the source — the cardinality lint's
        # retire-hook declaration for these model-labeled families
        for name in ("dks_registry_models", "dks_registry_requests_total",
                     "dks_registry_request_seconds_total",
                     "dks_registry_inflight", "dks_registry_sheds_total",
                     "dks_registry_swaps_total"):
            reg.declare_retirement(name)

    def _count_request(self, pending, error=None):
        """Per-request counter accounting, shared by _complete's live loop
        and the handler-side wedge claim so the two can never drift.
        Caller MUST hold ``_metrics_lock``."""

        self._m_requests.inc()
        self._m_rows.inc(pending.array.shape[0])
        if error is not None:
            self._m_errors.inc()
        elif self._cache is not None:
            (self._m_cache_hits if pending.cache_hit
             else self._m_cache_misses).inc()
        elapsed = time.monotonic() - pending.t_enqueued
        self._m_request_seconds.inc(elapsed)
        # the request's trace id rides as a bucket exemplar so an SLO
        # breach links straight to followable traces (None when tracing
        # is off — exemplar storage then never engages)
        exemplar = pending.trace.trace_id if pending.trace else None
        self._m_latency.observe(elapsed, exemplar=exemplar)
        self._m_class_latency.observe(elapsed, exemplar=exemplar,
                                      **{"class": pending.klass})
        # per-tenant cost accounting (model="default" in single-model
        # mode): requests / errors / rows / cache hits / latency
        self._costmeter.record_answer(
            pending.model.model_id if pending.model is not None else None,
            pending.array.shape[0], elapsed, error is not None,
            pending.cache_hit, exemplar=exemplar)
        if pending.model is not None:
            # per-tenant accounting on the version that ADMITTED the
            # request (hot-swap safe: the pin, not the active pointer)
            pending.model.record_answer(elapsed, error is not None)

    def _cache_key_for(self, array: np.ndarray,
                       wire_format: str = "json",
                       rm=None) -> Optional[str]:
        if self._cache is None:
            return None
        if rm is not None:
            # registry mode: the (model_id, version, content) fingerprint
            # the registry pinned at register time — cache hits are scoped
            # to the tenant AND the version, so a hot-swap makes the old
            # version's entries unreachable instead of stale
            key = request_cache_key(array, rm.fingerprint)
            return key if wire_format == "json" else f"{key}#{wire_format}"
        with self._model_fp_lock:
            model = self.model
            if self._model_fp is None or self._model_fp_model is not model:
                self._model_fp = model_fingerprint(model)
                self._model_fp_model = model
            fp = self._model_fp
        key = request_cache_key(array, fp)
        # the cache stores ENCODED payloads, so the negotiated format is
        # part of the identity: a binary client must never be served a
        # cached JSON document (and vice versa).  JSON keys keep the
        # historical unsuffixed form — pre-PR-6 cache semantics unchanged.
        return key if wire_format == "json" else f"{key}#{wire_format}"

    def _shed(self, reason: str, rm=None) -> None:
        self._m_sheds.inc(reason=reason)
        # per-tenant attribution of the same shed (model="default" when
        # no tenant routed — single-model mode)
        self._costmeter.record_shed(
            rm.model_id if rm is not None else None, reason)
        self._flight.record("shed", component="server", reason=reason)

    def _fail_request(self, pending, error: str, status: int) -> None:
        """Fail one request outside the batch path (deadline expiry): no
        device batch was involved, so ``batches_total`` must not move."""

        with self._metrics_lock:
            if pending.done:
                return
            pending.done = True
            self._count_request(pending, error)
        pending.error = error
        pending.status_code = status
        pending.event.set()

    def _answer_cached(self, pending, payload: str) -> bool:
        """Answer one request from the cache (dispatch-time recheck path).
        Returns False if something else already claimed it."""

        with self._metrics_lock:
            if pending.done:
                return False
            pending.done = True
            pending.cache_hit = True
            self._count_request(pending)
        pending.response = payload
        pending.event.set()
        return True

    def _complete(self, batch, payloads=None, error=None, status: int = 500,
                  index_map=None, device_rows: int = 0,
                  t_dispatch: Optional[float] = None,
                  t_fetch: Optional[float] = None, span_attrs=None,
                  cost=None):
        # tenant device-time attribution FIRST (no lock needed): the
        # fetch completing IS the block-until-ready boundary, so the
        # bracket closes at t_fetch even when the watchdog already
        # claimed the requests — the device work was genuinely consumed
        # and must bill its tenants either way
        if cost is not None and error is None and t_fetch is not None:
            self._costmeter.settle(cost[0], cost[1], t_end=t_fetch)
        # counters update BEFORE the response events: a client that gets
        # its answer and immediately scrapes /metrics must see itself
        # counted.  Claiming happens under the metrics lock so a batch the
        # watchdog failed and a late-returning finalize can never both
        # answer (or both count) the same request.
        with self._metrics_lock:
            live = [(i, p) for i, p in enumerate(batch) if not p.done]
            for _, p in live:
                p.done = True
            if not live:
                # a batch the watchdog already failed: the work still
                # finishing is itself the recovery signal
                with self._active_lock:
                    self._active.pop(id(batch), None)
                    self._last_progress = time.monotonic()
                    if error is None:
                        # the device demonstrably finished a full batch —
                        # that is what _ever_completed represents, so a
                        # first-batch wedge that later recovers must
                        # graduate from the generous first_batch_grace_s
                        # to the normal watchdog timeout
                        self._ever_completed = True
                if error is None:
                    if self._wedged.is_set():
                        logger.warning("serving recovered: a previously "
                                       "failed batch's device work completed")
                        self._wedged.clear()
                        self._flight.record("wedge_recovered",
                                            component="server")
                return
            self._m_batches.inc()
            for _, p in live:
                self._count_request(p, error)
        now = time.monotonic()
        with self._active_lock:
            self._active.pop(id(batch), None)
            self._last_progress = now
            if error is None:
                self._ever_completed = True
        if error is None:
            if device_rows:
                # feed admission's projected-wait gate: min of the two
                # windows is the better throughput estimate in both regimes
                # (completion-to-completion under pipelined load, where
                # dispatch-to-complete overcounts by the pipeline depth;
                # dispatch-to-complete after an idle gap, where the
                # completion gap includes the idle time)
                # concurrent finalizers race on _last_complete_t, so the
                # completion gap can come out negative — only fold it in
                # when it is a plausible (positive) window, else a
                # microscopic clamp would record millions of rows/s and
                # blind the projected-wait gate until the EWMA decays
                gap = now - self._last_complete_t
                window = now - t_dispatch if t_dispatch is not None else gap
                if 0 < gap < window:
                    window = gap
                if window > 0:
                    self._service_rate.observe(device_rows, window)
            self._last_complete_t = now
            if self._wedged.is_set():
                # the device answered again (relay unwedged): resume serving
                logger.warning("serving recovered: a batch completed after "
                               "the watchdog declared a wedge")
                self._wedged.clear()
                self._flight.record("wedge_recovered", component="server")
        tr = self._tracer
        to_audit = []
        for i, p in live:
            if error is not None:
                p.error = error
                p.status_code = status
            else:
                p.response = payloads[index_map[i] if index_map else i]
                if p.response:  # streamed answers finalize with b""
                    # chaos site ``engine.phi``: a numeric device fault —
                    # the payload is rewritten to a parsable-but-wrong
                    # answer BEFORE the waiter wakes, so the drill
                    # corrupts what is actually served and exercises the
                    # real detection path (resilience/faults.py)
                    if self._faults is not None and \
                            self._faults.fire("engine.phi") == "corrupt":
                        from distributedkernelshap_tpu.resilience.faults \
                            import corrupt_phi_payload

                        p.response = corrupt_phi_payload(
                            p.response,
                            seed=self._faults.hits("engine.phi"))
                # the invariant audit + cache insert run AFTER the
                # waiters wake (post-signal pass below): the screen
                # still gates the cache and still flags this very
                # answer, but its decode+check cost never sits on the
                # client-visible latency path
                to_audit.append(p)
            if tr.enabled and p.trace is not None and t_dispatch is not None:
                # per-request copies of the batch's device/finalize
                # timings: a batch can mix trace ids, so each request gets
                # children under ITS root rather than one orphan batch span
                end_fetch = t_fetch if t_fetch is not None else now
                tr.record_mono("server.device_explain", t_dispatch,
                               end_fetch, parent=p.trace,
                               batch_rows=device_rows,
                               # path (+ shared= for registry dispatches)
                               # from the dispatching deployment; legacy
                               # callers fall back to the bound model
                               **(span_attrs if span_attrs is not None
                                  else {"path": getattr(
                                      self.model, "explain_path", None)}),
                               error=error is not None)
                tr.record_mono("server.finalize", end_fetch,
                               time.monotonic(), parent=p.trace)
            p.event.set()
        for p in to_audit:
            if self._cache is not None and p.cache_key is not None:
                # keep-best: anytime answers carry their reported error
                # (final_err; 0.0 = full fidelity), and the cache only
                # serves an entry to budgets it satisfies.  screened=True:
                # the deferred audit queued below invalidates the entry
                # if the payload fails the invariant screen
                self._cache.put(p.cache_key, p.response,
                                est_err=getattr(p, "final_err", 0.0),
                                screened=True)
            if p.response:
                rm = p.model
                self._quality.enqueue_answer(
                    p.response,
                    model_id=(rm.model_id if rm is not None else None),
                    path=(rm.path if rm is not None
                          else getattr(self.model, "explain_path",
                                       "sampled")),
                    final_err=getattr(p, "final_err", 0.0),
                    rows=p.array,
                    model=(rm.model if rm is not None else self.model),
                    trace=(p.trace.trace_id if p.trace else None),
                    cache=self._cache, cache_key=p.cache_key)

    def _render_metrics(self) -> str:
        # rendered SOLELY by the shared registry (one renderer for the
        # whole process; the per-metric declarations live in
        # _register_metrics and the catalog in docs/OBSERVABILITY.md)
        return self.metrics.render()

    def _statusz_detail(self) -> dict:
        """Server-specific block of the ``/statusz`` payload: liveness
        state plus the queue/cache views an operator triages with."""

        with self._active_lock:
            ever_completed = self._ever_completed
        detail = {
            "wedged": self._wedged.is_set(),
            "ever_completed": ever_completed,
            "scheduling": type(self._sched).__name__,
            "queue_depths": dict(sorted(self._sched.depths().items())),
            "pipeline_depth": self.pipeline_depth or 0,
            "max_batch_size": self.max_batch_size,
            "admission_control": self._admission is not None,
            "staging": self._staging_enabled,
            # cross-tenant continuous batching actually in effect (the
            # knob only bites in registry mode)
            "shared_batching": (self._registry is not None
                                and self._shared_batching),
        }
        # the autoscaler's queue-pressure inputs: the admission EWMA's
        # device throughput and the EDF-aware projected wait per class
        # (rows sorting ahead of a fresh request of that class, over the
        # observed rate — the same projection admission sheds on, so the
        # scaler and the shedder can never disagree about "behind")
        rate = self._service_rate.rows_per_s()
        detail["service_rate_rows_per_s"] = (round(rate, 3)
                                             if rate else None)
        detail["rows_served_total"] = self._service_rate.rows_observed_total()
        if rate:
            detail["projected_wait_s"] = {
                klass: round(self._sched.rows_ahead(klass, None) / rate, 3)
                for klass in PRIORITY_CLASSES}
        else:
            detail["projected_wait_s"] = None
        with self._active_lock:
            detail["in_flight_batches"] = len(self._active)
        if self._cache is not None:
            detail["cache"] = self._cache.stats()
        detail["warmup"] = self.warmup_status()
        # engine-phase timings (profiling.py, populated under
        # DKS_PROFILE=1) + the always-on sampler's own health
        detail["profiler"] = {"phases": profiler().summary(),
                              "sampler": contprof().stats()}
        # the device-memory ledger panel: per-owner/per-model computed
        # bytes, budget/pressure state, device reconciliation gap
        detail["memory"] = memledger().snapshot()
        if self._registry is not None:
            # the multi-tenant panel: per-model active version, engine
            # path, fingerprint, in-flight pins, quota and drain state
            detail["registry"] = self._registry.statusz_panel()
        return detail

    def _split_batch_on_cache(self, batch):
        """Per-batch partial-hit splitting (``scheduling/result_cache.py``):
        answer rows that became cached while queued, collapse identical
        in-batch duplicates onto one computation, and return
        ``(live, leaders, index_map)`` — ``leaders`` are the requests that
        actually cost device work, ``index_map[i]`` maps each live request
        to its leader's payload slot."""

        live, leaders, index_map = [], [], []
        seen = {}
        for p in batch:
            if p.done:
                # answered elsewhere (wedge handling) — no device work
                continue
            key = p.cache_key
            if key is not None:
                payload = self._cache.get(key, max_err=p.budget)
                if payload is not None:
                    self._answer_cached(p, payload)
                    continue
                if key in seen:
                    # identical request already in this batch: share its
                    # computation (and its payload slot)
                    p.cache_hit = True
                    index_map.append(seen[key])
                    live.append(p)
                    continue
                seen[key] = len(leaders)
            index_map.append(len(leaders))
            leaders.append(p)
            live.append(p)
        return live, leaders, index_map

    # ------------------------------------------------------------------ #
    # precompile warmup ladder (cold-start subsystem; docs/PERFORMANCE.md)

    def _warming(self) -> bool:
        """True while the warmup ladder gates readiness (enabled and not
        yet finished — done/failed/aborted all release the gate)."""

        with self._warmup_lock:
            return self._warmup_state["state"] in ("pending", "running")

    def warmup_status(self) -> dict:
        """Snapshot of the warmup ladder for /healthz, /statusz and the
        warmup bench: enabled flag, state machine position, ladder sizes,
        completed rungs and the compile accounting delta."""

        with self._warmup_lock:
            st = dict(self._warmup_state)
            st["buckets"] = list(st["buckets"])
            st["completed_buckets"] = list(st["completed_buckets"])
            st["compile"] = dict(st["compile"])
        st["total"] = len(st["buckets"])
        st["completed"] = len(st["completed_buckets"])
        return st

    def _warmup_ladder(self, engine) -> list:
        """Every distinct compile bucket a dispatchable batch of up to
        ``max_batch_size`` rows can pad to, ascending (smallest first so
        interactive shapes warm earliest).  Uses the engine's own bucket
        function so the ladder can never drift from the padding the real
        dispatch applies; falls back to a pure power-of-two ladder for
        models that expose no engine."""

        top = max(1, self.max_batch_size)
        bucket = getattr(engine, "_bucket", None)
        if bucket is None or not getattr(
                getattr(engine, "config", None), "bucket_batches", True):
            sizes = {top}
            b = 1
            while b < top:
                sizes.add(b)
                b *= 2
            return sorted(sizes)
        return sorted({int(bucket(n)) for n in range(1, top + 1)})

    @staticmethod
    def _warmup_engine(model):
        """The engine whose ``background``/``_bucket`` the warmup ladder
        uses.  Looser than the classifier's ``serving_engine`` (which
        requires a ``predictor``): warmup only needs rows to tile and a
        bucket function, and test/stub models legitimately expose just
        that."""

        engine = getattr(getattr(model, "explainer", None),
                         "_explainer", None)
        if getattr(engine, "background", None) is None:
            # DistributedExplainer wraps the real engine one level down;
            # the ladder then comes from the inner engine's _bucket —
            # bucketing is idempotent, so those rungs cover every shape
            # _pad_sharded produces for real dispatches
            engine = getattr(engine, "engine", None)
        return engine

    @classmethod
    def _bucket_fn(cls, model):
        """The served model's engine compile-bucket function, or ``None``
        when its batches are not bucketed — the ONE resolution shared by
        the tenant-grouping policy (bucket-boundary packing) and the
        padded-rows accounting, so the eligibility rule cannot drift
        between them."""

        engine = cls._warmup_engine(model)
        bucket = getattr(engine, "_bucket", None)
        if bucket is None or not getattr(
                getattr(engine, "config", None), "bucket_batches", False):
            return None
        return bucket

    def _warmup_targets(self):
        """``(label, serving model, rm)`` triples the start-time ladder
        warms: every active registered model in registry mode (labels
        feed the ``model=<id>@vN`` compile-signature namespace) that a
        register-time ``_warm_model`` has not already warmed — the
        device work per rung is real even when the compiles are cache
        hits, so the ladder must not run twice per model — else the
        single bound model with no label."""

        if self._registry is not None:
            return [(rm.label, rm.model, rm)
                    for rm in self._registry.active_models()
                    if not rm.warmed]
        return [(None, self.model, None)]

    def _warm_rung(self, model, label, b: int, row: np.ndarray,
                   root=None) -> None:
        """One ladder rung for one model: trace+compile the bucket-``b``
        program under its declared compile signature
        (``[model=<label>,]rows=<b>[,path=...]``)."""

        from distributedkernelshap_tpu.runtime.compile_cache import (
            compile_events,
            shape_signature,
        )

        tr = self._tracer
        span = (tr.begin("warmup.bucket", parent=root, rows=b,
                         model=label)
                if tr.enabled else None)
        try:
            # the declared signature carries the deployment's evaluation
            # path AND (registry mode) its model namespace: the exact
            # entry and the sampled pipeline are distinct executables at
            # the same bucket, and so are two tenants' programs — the
            # compile accounting must attribute each rung to the one it
            # warmed
            sig = shape_signature(b, getattr(model, "explain_path", None),
                                  model=label)
            # pod models substitute their collective-safe warmup entry
            # (broadcast as _CMD_WARMUP so every process in the pod
            # compiles this rung in lockstep — a plain explain_batch here
            # would warm the followers through the pipelined async path
            # while /healthz still reads warming)
            warm_entry = getattr(model, "warmup_batch", None) \
                or model.explain_batch
            with profiler().phase("warmup"), \
                    compile_events().signature(sig):
                warm_entry(np.tile(row, (b, 1)), split_sizes=[b])
            # anytime deployments also warm their per-round entries at
            # this bucket (distinct executables from the single-shot
            # pipeline), declared under their own rounds=<k> suffix so
            # the compile accounting attributes each rung honestly
            if getattr(model, "supports_anytime", False) and \
                    hasattr(model, "anytime_warm"):
                try:
                    n_rounds = model.anytime_rounds()
                    if n_rounds:
                        asig = shape_signature(
                            b, f"sampled,rounds={n_rounds}", model=label)
                        with profiler().phase("warmup"), \
                                compile_events().signature(asig):
                            model.anytime_warm([b])
                except Exception:
                    logger.exception("anytime warmup rung failed; round "
                                     "entries will compile on first use")
        finally:
            if span is not None:
                tr.end(span)

    def _warm_model(self, rm) -> None:
        """Warm ONE registered model's full compile ladder — the
        registry's hot-swap path: version N+1 compiles its executables
        (under its own ``model=`` signature namespace) while version N
        keeps serving, so the atomic flip lands on warm programs.  Runs
        on the registering thread; the new version's engine is not yet
        dispatched by anyone else, and concurrent device work from the
        live dispatcher serialises at the device like any other caller."""

        engine = self._warmup_engine(rm.model)
        bg = getattr(engine, "background", None)
        if bg is None or not hasattr(rm.model, "explain_batch"):
            logger.warning("cannot warm %s: it exposes no engine "
                           "background; it will serve cold", rm.label)
            return
        ladder = [int(b) for b in self._warmup_ladder(engine)]
        row = np.asarray(bg[:1], dtype=np.float32)
        t0 = time.monotonic()
        for b in ladder:
            if self._stop.is_set():
                return
            self._warm_rung(rm.model, rm.label, b, row)
        rm.warmed = True  # the start-time ladder then skips this model
        self._flight.record("warmup", component="server", state="done",
                            model=rm.label, buckets=ladder)
        logger.info("warmed %s: buckets %s in %.1fs", rm.label, ladder,
                    time.monotonic() - t0)

    def _run_warmup(self) -> None:
        """Trace+compile the engine over the bucket ladder (dispatcher
        thread, before the batch loop — the engine's jit caches are
        single-dispatcher state, so warmup must run exactly where real
        dispatches will).  Requests arriving meanwhile park in the
        scheduler; the readiness gate keeps routers away.  Failure is
        logged and serving proceeds — a broken warmup must never be worse
        than no warmup."""

        st = self._warmup_state
        if not st["enabled"]:
            return
        from distributedkernelshap_tpu.runtime.compile_cache import (
            compile_events,
        )

        ce = compile_events()
        before = ce.snapshot()
        t0 = time.monotonic()
        tr = self._tracer
        root = tr.begin("server.warmup") if tr.enabled else None
        state = "failed"
        try:
            # registry mode warms EVERY active model's ladder (each with
            # its own model=<label> compile signatures), so the whole
            # roster is routable-warm when the readiness gate releases;
            # single-model mode keeps the historical one-ladder behaviour
            targets, warmable = self._warmup_targets(), 0
            if not targets:
                # every registered model was already warmed at register
                # time: the gate releases with nothing to do
                state = "done"
                return
            ladders = []
            for label, model, rm in targets:
                engine = self._warmup_engine(model)
                bg = getattr(engine, "background", None)
                if bg is None or not hasattr(model, "explain_batch"):
                    logger.warning(
                        "warmup: %s exposes no engine background; "
                        "serving it cold", label or "model")
                    continue
                ladders.append((label, model, rm,
                                self._warmup_ladder(engine),
                                np.asarray(bg[:1], dtype=np.float32)))
                warmable += 1
            if not warmable:
                raise RuntimeError(
                    "model exposes no engine background to warm with")
            with self._warmup_lock:
                st["state"] = "running"
                st["buckets"] = [int(b) for _, _, _, ladder, _ in ladders
                                 for b in ladder]
            with _tracing.use_context(root.context if root is not None
                                      else None):
                for label, model, rm, ladder, row in ladders:
                    for b in ladder:
                        if self._stop.is_set():
                            state = "aborted"
                            return
                        with self._warmup_lock:
                            st["current"] = int(b)
                        self._warm_rung(model, label, int(b), row,
                                        root=root)
                        # warmup progress IS device progress — keep the
                        # watchdog's view current through a long ladder
                        with self._active_lock:
                            self._last_progress = time.monotonic()
                        with self._warmup_lock:
                            st["completed_buckets"].append(int(b))
                            st["current"] = None
                            st["elapsed_s"] = round(
                                time.monotonic() - t0, 3)
                    if rm is not None:
                        rm.warmed = True
            state = "done"
        except Exception as e:
            logger.exception("warmup ladder failed; serving cold")
            with self._warmup_lock:
                st["error"] = str(e)
        finally:
            delta = ce.delta(before, ce.snapshot())
            with self._warmup_lock:
                st["state"] = state
                st["elapsed_s"] = round(time.monotonic() - t0, 3)
                st["compile"] = {
                    "fresh": int(delta["totals"].get("fresh", 0)),
                    "cache_hit": int(delta["totals"].get("cache_hit", 0)),
                    "seconds": round(
                        sum(delta["seconds_totals"].values()), 3)}
                compile_summary = dict(st["compile"])
                done = list(st["completed_buckets"])
            self._flight.record("warmup", component="server", state=state,
                                buckets=done, **compile_summary)
            if root is not None:
                tr.end(root, state=state, **compile_summary)
            if state == "done":
                logger.info(
                    "warmup ladder done: buckets %s in %.1fs (%d fresh "
                    "compiles, %d persistent-cache hits, %.1fs compiling)",
                    done, time.monotonic() - t0, compile_summary["fresh"],
                    compile_summary["cache_hit"],
                    compile_summary["seconds"])

    def _refresh_tenant_slos(self) -> None:
        """Re-template the per-tenant SLO set from the registry's
        current roster (``ModelRegistry`` calls this after every
        registration and removal).  Only with the DEFAULT SLO set — an
        explicit ``slos=`` override is the operator's contract and is
        never rewritten.  Surviving SLOs keep their alert state (see
        ``HealthEngine.set_slos``); a removed tenant's SLOs stop being
        evaluated, which is the stale-label retirement's SLO-layer
        twin."""

        if self._registry is None or not self._auto_slos:
            return
        try:
            self.health.set_slos(default_server_slos(
                tenants=self._registry.model_ids()))
        except Exception:
            logger.exception("per-tenant SLO refresh failed; the previous "
                             "SLO set stays in effect")

    def _group_key_for(self, rm):
        """The dispatch-group identity of a pinned tenant version:
        ``("share", key)`` for shared-program-eligible deployments when
        cross-tenant batching is on (content-identical tenants coalesce
        onto one device call), else the stable ``("model", id, version)``
        — deterministic across runs and a usable metric/trace label,
        unlike the historical ``id(p.model)`` key (alive-safe via the
        pin, but non-reproducible).  ``None`` in single-model mode."""

        if rm is None:
            return None
        share = getattr(rm, "share_key", None)
        if self._shared_batching and share and self._registry is not None \
                and self._registry.share_peers(share) > 1:
            # only with a live peer: a lone eligible tenant keeps its
            # per-model group so its quota's per-cycle cap still bites
            return ("share", share)
        return ("model", rm.model_id, rm.version)

    def _form_batch(self):
        """Pop one schedulable batch: expired requests are failed (504),
        cache hits answered and in-batch duplicates collapsed.  Returns a
        list of ``(live, leaders, index_map, t_claim, rm, shared)``
        groups — one per dispatch-group key appearing in the popped batch
        (a device call is one engine's program; with cross-tenant
        batching on, content-identical tenants SHARE a group and ``rm``
        is the EDF-first member's pinned version, whose engine serves the
        whole group's constants bit-identically; ``shared`` flags a group
        actually spanning >1 tenant) — or ``None`` when nothing
        dispatchable came out (idle wakeup, all-expired, all-cached).
        ``rm`` is ``None`` in single-model mode, where the list has one
        group."""

        grouping = (self._grouping
                    if self._registry is not None and self._shared_batching
                    else None)
        batch, expired = self._sched.next_batch(
            self.max_batch_size,
            max_rows=getattr(self.model, "max_rows", None),
            batch_timeout_s=self.batch_timeout_s, stop=self._stop,
            grouping=grouping)
        tr = self._tracer
        t_claim = time.monotonic()
        for p in expired:
            if getattr(p, "anytime", None) is not None and \
                    p.anytime.last_result is not None:
                # degrade before shed: a refining request whose deadline
                # passed while requeued already HAS an answer with honest
                # error bars — send the last partial instead of a 504
                if tr.enabled and p.trace is not None:
                    tr.record_mono("server.queue_wait", p.t_enqueued,
                                   t_claim, parent=p.trace, expired=True)
                self._finish_anytime(p, "deadline")
                continue
            # the declared SLO is already missed: answering late would
            # waste a device slot on a response the client has abandoned
            self._shed("deadline_expired", rm=p.model)
            if tr.enabled and p.trace is not None:
                tr.record_mono("server.queue_wait", p.t_enqueued,
                               t_claim, parent=p.trace, expired=True)
            self._fail_request(p, "deadline expired before dispatch "
                              "(server overloaded)", 504)
        if not batch:
            return None
        # group by dispatch key, preserving EDF pop order within and
        # across groups (dict preserves first-seen insertion order); the
        # grouped scheduler path memoised each request's key already
        by_key = {}
        for p in batch:
            key = getattr(p, "group_key", None)
            if key is None:
                key = self._group_key_for(p.model)
            by_key.setdefault(key, []).append(p)
        groups = []
        for key, members in by_key.items():
            live, leaders, index_map = self._split_batch_on_cache(members)
            if leaders:
                # the dispatching version must come from a LIVE leader:
                # its pin is held until _complete answers it, so a
                # hot-swap drain can never retire/release the engine
                # under this device call (a cache-answered members[0]
                # would already have released its pin)
                rm = leaders[0].model
                shared = None if rm is None else (
                    key[0] == "share"
                    and len({(m.model.model_id, m.model.version)
                             for m in live}) > 1)
                groups.append((live, leaders, index_map, t_claim, rm,
                               shared))
        if groups:
            self._m_batch_groups.observe(len(groups))
        return groups or None

    def _dispatch_batch(self, live, leaders, index_map, t_claim,
                        stacked=None, staged=None, rm=None, shared=None):
        """Dispatch one formed batch to the device (dispatcher thread only:
        the engine's jit caches are single-dispatcher state).  ``stacked``
        /``staged`` come pre-built from the staging batcher; without them
        the rows are stacked here (the classic single-thread path).
        ``rm`` is the batch's registered model (registry mode) — for a
        shared-program group, the EDF-first member's pinned version,
        whose engine runs the whole group (every member pinned its OWN
        version at admission, so accounting and the drain contract are
        per-tenant regardless).  ``shared`` flags a group spanning >1
        tenant (the ``shared=`` span attribute)."""

        # read at dispatch: tests may swap self.model while the
        # dispatcher is parked in next_batch / the staging buffer
        model = rm.model if rm is not None else self.model
        if len(live) == 1 and getattr(live[0], "anytime_on", False):
            # anytime pendings form singleton groups (unique group_key):
            # one refinement round per scheduler turn, requeued between
            # rounds so earlier-deadline work preempts.  Falls through to
            # the classic dispatch when the run cannot begin.
            if self._dispatch_anytime(live[0], rm):
                return
        pipelined = hasattr(model, "explain_batch_async")
        tr = self._tracer
        sizes = [p.array.shape[0] for p in leaders]
        with self._active_lock:
            # registered BEFORE the device call so the watchdog can
            # fail it if the call never returns
            self._active[id(live)] = live
        t_dispatch = time.monotonic()
        device_rows = sum(sizes)
        # bucket-padding accounting: the rows the engine will pad on top
        # of the real request rows (the waste the cross-tenant packer
        # minimizes), attributed to the dispatching tenant
        bucket = self._bucket_fn(model)
        if bucket is not None:
            try:
                self._m_padded_rows.inc(
                    max(0, int(bucket(device_rows)) - device_rows),
                    model=rm.model_id if rm is not None else "default")
            except Exception:
                pass
        span_attrs = {"path": getattr(model, "explain_path", None)}
        if shared is not None:
            span_attrs["shared"] = bool(shared)
        # cost-attribution bracket: monotonic + compile-seconds snapshot
        # opened just before the device call, settled at fetch; shares =
        # per-tenant (model, version, path, rows) from the leaders (the
        # split_sizes view) so a shared cross-tenant batch prorates by
        # row share
        cost_tx = self._costmeter.begin()
        cost = ((cost_tx, dispatch_shares(leaders,
                                          default_path=span_attrs["path"]))
                if cost_tx is not None else None)
        if tr.enabled:
            for p in live:
                if p.trace is not None:
                    tr.record_mono("server.queue_wait", p.t_enqueued,
                                   t_claim, parent=p.trace)
                    tr.record_mono("server.schedule", t_claim,
                                   t_dispatch, parent=p.trace,
                                   batch_requests=len(live))
        # engine profiling phases fired during the device call
        # parent to one traced request of the batch (attrs carry
        # the batch size; a batch can mix trace ids)
        batch_ctx = next((p.trace for p in leaders
                          if p.trace is not None), None) \
            if tr.enabled else None
        # per-leader response encodings, only for models that speak the
        # wire protocol (the serving wrappers); stub models keep the
        # historical JSON-only call signature.  An all-JSON batch also
        # omits the kwarg, so pre-wire model subclasses overriding
        # explain_batch(_async) without `formats` keep working for the
        # traffic they can serve.
        formats = ([p.wire_format for p in leaders]
                   if getattr(model, "supports_wire_formats", False)
                   else None)
        kwargs = ({"formats": formats} if formats is not None
                  and any(f != "json" for f in formats) else {})
        try:
            if stacked is None:
                stacked = np.concatenate([p.array for p in leaders],
                                         axis=0)
            if pipelined:
                with _tracing.use_context(batch_ctx):
                    finalize = model.explain_batch_async(
                        staged if staged is not None else stacked,
                        split_sizes=sizes, **kwargs)
                self._inflight.put((live, finalize, index_map,
                                    device_rows, t_dispatch,
                                    batch_ctx, span_attrs, cost))
            else:
                with _tracing.use_context(batch_ctx):
                    payloads = model.explain_batch(
                        stacked, split_sizes=sizes, **kwargs)
                self._complete(
                    live, payloads,
                    index_map=index_map, device_rows=device_rows,
                    t_dispatch=t_dispatch,
                    t_fetch=time.monotonic(), span_attrs=span_attrs,
                    cost=cost)
        except Exception as e:  # surface errors to waiting requests
            logger.exception("explain batch failed")
            self._complete(live, error=str(e))

    # ------------------------------------------------------------------ #
    # anytime refinement dispatch (ISSUE 16)

    def _dispatch_anytime(self, p, rm) -> bool:
        """Run ONE refinement round for an anytime pending (dispatcher
        thread — the round entries live in the engine's jit caches).

        Returns ``False`` (caller falls through to the classic one-shot
        dispatch) when the engine cannot refine this request after all.
        Otherwise the round runs, a partial frame streams out if the
        client asked, and the pending either finishes (budget met /
        deadline imminent / schedule exhausted — first wins) or requeues
        at the scheduler, where the round boundary is an EDF preemption
        point."""

        model = rm.model if rm is not None else self.model
        if p.anytime is None:
            try:
                p.anytime = model.anytime_begin(p.array)
            except Exception:
                logger.exception("anytime_begin failed; serving the "
                                 "request single-shot")
                p.anytime = None
            if p.anytime is None:
                p.anytime_on = False
                return False
        run = p.anytime
        batch = [p]
        with self._active_lock:
            # registered like any device batch so the watchdog can fail
            # a wedged round
            self._active[id(batch)] = batch
        t_dispatch = time.monotonic()
        cost_tx = self._costmeter.begin()
        try:
            with _tracing.use_context(p.trace):
                result = run.step()
        except Exception as e:
            logger.exception("anytime round failed")
            self._complete(batch, error=str(e))
            if p.stream:
                p.frames.put(None)
            return True
        t_fetch = time.monotonic()
        if cost_tx is not None:
            # per-round cost bracket: every round bills its tenant as it
            # runs, so a preempted request's spend is never orphaned
            self._costmeter.settle(
                cost_tx, dispatch_shares([p], default_path="sampled"),
                t_end=t_fetch)
        with self._active_lock:
            self._active.pop(id(batch), None)
            self._last_progress = t_fetch
            self._ever_completed = True
        self._m_anytime_rounds.inc()
        if self._tracer.enabled and p.trace is not None:
            self._tracer.record_mono(
                "anytime.round", t_dispatch, t_fetch, parent=p.trace,
                round=result.round_index,
                nsamples=result.cumulative_nsamples,
                max_err=result.max_err)
        # stop rule: first of {error budget met, deadline imminent,
        # schedule exhausted}.  "Imminent" projects the next round at 2x
        # the last one (geometric draw growth): starting a round that
        # cannot finish by the deadline would turn a servable request
        # into a 504.
        reason = None
        if p.budget is not None and result.max_err <= p.budget:
            reason = "budget_met"
        if reason is None and result.done:
            reason = "exhausted"
        if reason is None and p.deadline is not None and \
                time.monotonic() + 2.0 * run.last_round_s > p.deadline:
            reason = "deadline"
        if reason is not None:
            self._finish_anytime(p, reason, t_dispatch=t_dispatch,
                                 t_fetch=t_fetch)
            return True
        if p.stream:
            p.frames.put(model.anytime_frame(result, final=False))
        # preemption point: back into the EDF queue — an earlier-deadline
        # arrival runs before this request's next round
        self._sched.requeue(p)
        return True

    def _finish_anytime(self, p, reason: str,
                        t_dispatch: Optional[float] = None,
                        t_fetch: Optional[float] = None) -> None:
        """Answer an anytime pending from its latest round result and
        account the stop: final payload (or final stream frame), fidelity
        recorded for the keep-best cache, ``refine_stopped`` flight
        event + stop-reason counter + final-error histogram (the
        error-budget SLO's input)."""

        run = p.anytime
        result = run.last_result
        model = p.model.model if p.model is not None else self.model
        p.final_err = result.max_err
        exemplar = p.trace.trace_id if p.trace else None
        self._m_anytime_refines.inc(reason=reason)
        self._m_anytime_final_err.observe(result.max_err,
                                          exemplar=exemplar)
        self._flight.record("refine_stopped", component="server",
                            reason=reason, rounds=run.rounds_run,
                            max_err=round(result.max_err, 6))
        try:
            if p.stream:
                p.frames.put(model.anytime_frame(result, final=True))
                payload = b""  # the frames ARE the response body
            else:
                payload = model.anytime_payload(p.array, result,
                                                fmt=p.wire_format)
        except Exception as e:
            logger.exception("anytime finalize failed")
            self._complete([p], error=str(e))
            if p.stream:
                p.frames.put(None)
            return
        self._complete([p], payloads=[payload], index_map=[0],
                       device_rows=p.array.shape[0],
                       t_dispatch=t_dispatch, t_fetch=t_fetch,
                       span_attrs={"path": "sampled", "anytime": True,
                                   "stop": reason})
        if p.stream:
            p.frames.put(None)

    def _batcher_loop(self):
        """Staging half of the double-buffered pipeline (staging enabled
        only): form scheduler batches, stack their rows, and start the
        host→device upload (``model.stage_rows`` → ``jax.device_put``,
        asynchronous) while the dispatcher thread's current batch is still
        computing.  The bounded :class:`StagingBuffer` is the double
        buffer: one batch computing, one staged, one forming."""

        register_thread_role("batcher")
        tr = self._tracer
        # dks: allow(DKS-C005): deliberate fail-fast — see the comment below
        while not self._stop.is_set():
            # deliberately NO try around batch formation: an exception in
            # next_batch/cache-split has already popped requests this
            # frame holds no reference to — swallowing it would leak them
            # into a silent per-request hang.  Propagating kills the
            # batcher loudly, exactly the unstaged dispatch loop's
            # fail-fast behaviour.
            formed = self._form_batch()
            if formed is None:
                continue
            # per-tenant device-stream overlap for N-group cycles: stack
            # every group on the host first, then run the H2D uploads as
            # a pipeline staying ``staging_slots`` groups AHEAD of the
            # blocking buffer puts — tenant B's (and C's...) uploads are
            # in flight while tenant A's group computes, yet in-flight
            # staged device buffers stay bounded by the configured depth
            # (stage-everything-upfront would hold one buffer per group
            # regardless of the knob)
            items = []
            for live, leaders, index_map, t_claim, rm, shared in formed:
                try:
                    stacked = np.concatenate([p.array for p in leaders],
                                             axis=0)
                except Exception as e:
                    # from here on this frame OWNS the popped requests:
                    # any failure must answer them, not drop them
                    logger.exception("staging batcher: stacking failed")
                    self._complete(live, error=str(e))
                    continue
                items.append([live, leaders, index_map, t_claim,
                              stacked, None, rm, shared])

            def _stage(item):
                # NOTHING may escape: staging is an optimisation — any
                # failure (upload, span recording, capability probe)
                # must degrade to the classic dispatch-time H2D, never
                # fail the batch or kill this thread (the batcher is the
                # sole batch former while staging is on)
                try:
                    leaders, stacked, rm = item[1], item[4], item[6]
                    model = rm.model if rm is not None else self.model
                    stage = getattr(model, "stage_rows", None)
                    if stage is None:
                        return
                    t0 = time.monotonic()
                    try:
                        item[5] = stage(stacked)
                    except Exception:
                        logger.exception(
                            "stage_rows failed; dispatching unstaged")
                        return
                    if tr.enabled and item[5] is not None:
                        batch_ctx = next((p.trace for p in leaders
                                          if p.trace is not None), None)
                        if batch_ctx is not None:
                            tr.record_mono("staging.upload", t0,
                                           time.monotonic(),
                                           parent=batch_ctx,
                                           rows=int(stacked.shape[0]))
                except Exception:
                    logger.exception("staging probe failed; "
                                     "dispatching unstaged")

            ahead = getattr(self, "_staging_slots", 1)
            for i in range(min(ahead, len(items))):
                _stage(items[i])
            for i, item in enumerate(items):
                if not self._staged.put(tuple(item), stop=self._stop):
                    # shutdown won the race for the staging slot: fail
                    # this and every remaining staged batch like the
                    # scheduler drain would have
                    for it in items[i:]:
                        self._complete(it[0],
                                       error="server shutting down",
                                       status=503)
                    return
                if i + ahead < len(items):
                    _stage(items[i + ahead])

    def _dispatch_loop(self):
        """Form batches via the scheduler and dispatch one device call each.

        Dispatch-only: the device work is launched asynchronously and the
        ``(batch, finalize)`` pair is handed to the finalizer pool, so batch
        k+1's dispatch overlaps batch k's D2H fetch + postprocess — the fetch
        is ~70ms of RPC latency on a tunnelled TPU and concurrent fetches
        overlap, so pipelining collapses the per-batch round-trip cost.

        With staging enabled, batch formation + stacking + H2D move to
        :meth:`_batcher_loop` and this thread consumes the staging buffer —
        each batch it dispatches already has device-resident rows."""

        register_thread_role("dispatcher")
        try:
            # precompile warmup ladder first: this thread owns the engine's
            # jit caches, and the readiness gate (/healthz "warming") keeps
            # routers away while it runs; queued requests wait in the
            # scheduler and land on warm programs
            self._run_warmup()
            if self._staging_enabled:
                # deliberate fail-fast — a formation exception has already
                # popped requests this frame holds no reference to;
                # swallowing it would leak them into silent per-request
                # hangs, while propagation kills the dispatcher loudly and
                # the finally still drains staged leftovers.
                # dks: allow(DKS-C005): deliberate fail-fast (see above)
                while True:
                    got = self._staged.get(stop=self._stop)
                    if got is None:
                        break
                    (live, leaders, index_map, t_claim,
                     stacked, staged, rm, shared), ready_s = got
                    # time the staged batch sat device-ready while this
                    # thread was busy with the previous one — the measured
                    # upload/compute overlap
                    self._m_staging_overlap.inc(ready_s)
                    self._dispatch_batch(live, leaders, index_map, t_claim,
                                         stacked=stacked, staged=staged,
                                         rm=rm, shared=shared)
                for item in self._staged.drain():
                    # staged but never dispatched (shutdown): fail like the
                    # scheduler drain so no handler thread leaks
                    self._complete(item[0], error="server shutting down",
                                   status=503)
                return
            # deliberate fail-fast — same contract as the staged branch
            # above (dispatch errors are guarded inside _dispatch_batch; a
            # formation error must not be swallowed).
            # dks: allow(DKS-C005): deliberate fail-fast (see above)
            while not self._stop.is_set():
                formed = self._form_batch()
                if formed is None:
                    continue
                for live, leaders, index_map, t_claim, rm, shared in formed:
                    self._dispatch_batch(live, leaders, index_map,
                                         t_claim, rm=rm, shared=shared)
        finally:
            # finalizers only exit once dispatch can no longer enqueue, so a
            # batch dispatched during shutdown is still fetched + answered
            self._dispatch_done.set()

    def _finalize_loop(self):
        """Fetch + postprocess dispatched batches (several of these run so
        D2H round trips overlap)."""

        register_thread_role("finalizer")
        while not (self._dispatch_done.is_set() and self._inflight.empty()):
            try:
                (batch, finalize, index_map, device_rows,
                 t_dispatch, batch_ctx, span_attrs,
                 cost) = self._inflight.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                with _tracing.use_context(batch_ctx):
                    payloads = finalize()
                self._complete(batch, payloads, index_map=index_map,
                               device_rows=device_rows,
                               t_dispatch=t_dispatch,
                               t_fetch=time.monotonic(),
                               span_attrs=span_attrs, cost=cost)
            except Exception as e:
                logger.exception("finalize batch failed")
                self._complete(batch, error=str(e))

    def _watchdog_loop(self):
        """Fault isolation for a one-process serving deployment.

        The reference's Ray Serve replicas fail independently (a crashed
        replica's requests error; the rest keep serving,
        ``explainers/wrappers.py:10-88`` + ``restartPolicy: Always``).  Here
        one process owns the device, so a wedged device call — a dead relay
        tunnel mid-RPC, a backend restart — would otherwise hold every
        in-flight request's socket open forever.  This loop watches for
        dispatched work that stops progressing, fails the affected requests
        with a fast error, flips the server into a wedged state (fast 503s,
        failing ``/healthz``), and drops the model's device-resident state
        so a recovered backend starts from clean buffers.  The blocked OS
        thread itself is unrecoverable (an XLA call cannot be cancelled) —
        if it eventually returns, ``_complete`` notices and clears the
        wedge; if it never does, the failing ``/healthz`` gets the pod
        restarted (``cluster/tpu_serve_cluster.yaml``)."""

        register_thread_role("tick")
        while not self._stop.is_set():
            if self._stop.wait(min(1.0, self.watchdog_timeout_s / 4)):
                break
            try:
                self._watchdog_tick()
            except Exception:
                # the watchdog IS the wedge detector: a transient raise
                # (a dying registry mid-swap, a torn model reset) must
                # cost one tick, never the thread — a silently dead
                # watchdog turns the next device wedge into an
                # every-socket-hangs-forever outage (DKS-C005)
                logger.exception("watchdog tick failed")

    def _watchdog_tick(self):
        """One stall evaluation (see :meth:`_watchdog_loop`)."""

        # progress markers are written by finalizer threads (_complete)
        # and read by health/statusz handlers: all under _active_lock
        # (DKS-C001) so a stall age can never pair a torn marker set
        with self._active_lock:
            active = list(self._active.values())
            if not active:
                self._last_progress = time.monotonic()
                return
            stalled_s = time.monotonic() - self._last_progress
            # before the first completed batch, allow the first-compile
            # grace window instead of the steady-state timeout
            limit = (self.watchdog_timeout_s if self._ever_completed
                     else self.first_batch_grace_s)
        if stalled_s <= limit:
            return
        logger.error(
            "watchdog: %d in-flight batch(es) made no progress for "
            "%.0f s; failing them and marking the server wedged",
            len(active), stalled_s)
        self._wedged.set()
        self._m_wedges.inc()
        self._flight.record("wedge", component="server",
                            stalled_s=round(stalled_s, 1),
                            in_flight_batches=len(active))
        msg = (f"device call exceeded the {limit:.0f}s "
               f"watchdog timeout; server marked unhealthy")
        for batch in active:
            self._complete(batch, error=msg)
        # requests parked behind the wedged dispatcher never reach a
        # device call: fail them too instead of letting them wait out
        # the pod restart (new arrivals fast-503 via the handler)
        drained = self._sched.drain()
        if drained:
            self._complete(drained, error=msg, status=503)
        if self._registry is not None:
            # fleet-wide: every active tenant's device caches ride the
            # same (possibly restarted) backend
            self._registry.reset_all()
        reset = getattr(self.model, "reset", None)
        if reset is not None:
            try:
                reset()
            except Exception:
                logger.exception("model reset after wedge failed")

    def _device_probe_ok(self) -> bool:
        """One tiny device round trip, bounded by ``device_probe_timeout_s``.

        A wedged backend turns the probe into an indefinite hang inside the
        XLA runtime, which cannot be interrupted — so the probe runs on a
        daemon thread and at most ONE probe thread exists.  Concurrent
        health checks (k8s points readiness AND liveness at ``/healthz``,
        so probes can coincide) JOIN the in-flight probe and share its
        verdict; only a probe that has already outlived its own timeout
        fails later callers fast."""

        with self._probe_lock:
            t = self._probe_thread
            if t is not None and t.is_alive():
                age = time.monotonic() - self._probe_started
                if age > self.device_probe_timeout_s:
                    return False  # stuck probe: the device is not answering
                done = self._probe_done
            else:
                done = threading.Event()

                def probe():
                    try:
                        import jax.numpy as jnp

                        np.asarray(jnp.zeros((), jnp.float32) + 1.0)
                        done.set()
                    except Exception:
                        logger.exception("health device probe failed")

                self._probe_done = done
                self._probe_started = time.monotonic()
                self._probe_thread = threading.Thread(target=probe,
                                                      daemon=True)
                self._probe_thread.start()
        return done.wait(self.device_probe_timeout_s)

    def _health(self):
        """(status_code, payload) for ``/healthz``: wedged state, then the
        in-flight-progress shortcut, then a bounded device round trip.

        Busy is not wedged: under sustained load the probe op would queue
        behind all in-flight device work and time out on a perfectly
        healthy pod — but recent batch progress is itself proof the device
        answers, so the probe is skipped while work is flowing."""

        if self._wedged.is_set():
            return 503, {"status": "wedged",
                         "error": "device made no progress within the "
                                  "watchdog timeout"}
        if self._warming():
            # not-ready, not broken: the prober must not route here yet and
            # an orchestrator must not restart a replica that is merely
            # compiling its ladder — the distinct status string is the
            # contract ReplicaManager._wait_healthy keys on
            return 503, {"status": "warming", "warmup": self.warmup_status()}
        with self._active_lock:
            busy = bool(self._active)
            last_progress = self._last_progress
        if busy and (time.monotonic() - last_progress
                     < self.watchdog_timeout_s):
            return 200, {"status": "ok", "detail": "in-flight work "
                         "progressing; device probe skipped"}
        if not self._device_probe_ok():
            return 503, {"status": "device-unreachable",
                         "error": f"device round trip exceeded "
                                  f"{self.device_probe_timeout_s:.1f}s"}
        return 200, {"status": "ok"}

    def _make_handler(server):  # noqa: N805 - closure over the server
        class Handler(BaseHTTPRequestHandler):
            # keep-alive: clients reuse one connection for their whole request
            # stream, so the server runs a handful of long-lived handler
            # threads instead of spawning one per request
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body, ctype="application/json",
                       headers=None):
                # the request's root span (set only on the /explain route)
                # ends with the reply, whatever branch produced it
                span = self.__dict__.pop("_dks_root", None)
                if span is not None:
                    server._tracer.end(span, status=code)
                # binary wire payloads arrive as bytes; everything else is
                # the historical str
                data = body if isinstance(body, (bytes, bytearray)) \
                    else body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(data)

            def _reply_explain_ok(self, body, rm=None):
                """Success reply for /explain, routed through the chaos
                site ``server.explain``: crash/hang/slow happen inside
                ``fire``; ``drop`` closes the socket without replying
                (mid-request connection loss); ``corrupt`` garbles the
                payload bytes under an intact Content-Length.

                The payload's TYPE is the transport truth: wire-encoded
                explanations are bytes (Content-Type
                ``application/x-dks-wire``), the historical Explanation
                document a str (JSON) — so a model swap mid-flight can
                never mislabel a payload."""

                binary = isinstance(body, (bytes, bytearray))
                ctype = _wire.CONTENT_TYPE if binary else "application/json"
                action = (server._faults.fire("server.explain")
                          if server._faults is not None else None)
                if action == "drop":
                    span = self.__dict__.pop("_dks_root", None)
                    if span is not None:
                        server._tracer.end(span, status=0, dropped=True)
                    self.close_connection = True
                    return
                # counted only for responses actually sent (a chaos drop
                # above never puts these bytes on the wire)
                server._m_wire_bytes.inc(
                    len(body), format="binary" if binary else "json",
                    direction="tx")
                server._costmeter.record_wire(
                    rm.model_id if rm is not None else None, "tx",
                    len(body))
                if action != "corrupt":
                    self._reply(200, body, ctype=ctype)
                    return
                from distributedkernelshap_tpu.resilience.faults import (
                    corrupt_payload,
                )

                span = self.__dict__.pop("_dks_root", None)
                if span is not None:
                    server._tracer.end(span, status=200, corrupt=True)
                # raw-bytes variant of _reply: the garbled payload is not
                # valid text, so it cannot round-trip through str
                data = corrupt_payload(body if binary else body.encode())
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _reply_explain_stream(self, pending, rm):
                """Streamed /explain reply: one chunked-transfer DKSS
                frame per refinement round as the dispatcher produces
                them (``pending.frames``), terminated after the frame
                marked final.  Falls back to an ordinary single response
                when refinement never engaged (``pending.response`` set
                with no frames) — the client's downgrade path.  A failure
                after frames went out tears the stream (connection close,
                no final frame), which the client-side decoder rejects —
                a torn stream must never be mistaken for a complete
                answer."""

                headers_sent = False
                while True:
                    try:
                        item = pending.frames.get(timeout=0.5)
                    except queue.Empty:
                        if pending.event.is_set() and pending.frames.empty():
                            # answered without streaming (fallback /
                            # drain paths push no terminal sentinel)
                            break
                        if server._stop.is_set() or server._wedged.is_set():
                            with server._metrics_lock:
                                if not pending.done:
                                    pending.done = True
                                    pending.error = (
                                        "server shutting down"
                                        if server._stop.is_set() else
                                        "server wedged: device made no "
                                        "progress within the watchdog "
                                        "timeout")
                                    pending.status_code = 503
                                    server._count_request(pending,
                                                          pending.error)
                            if pending.error is not None:
                                break
                        continue
                    if item is None:  # terminal sentinel from the server
                        break
                    if not headers_sent:
                        span = self.__dict__.pop("_dks_root", None)
                        if span is not None:
                            server._tracer.end(span, status=200)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         _wire.STREAM_CONTENT_TYPE)
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        headers_sent = True
                    self.wfile.write(b"%x\r\n" % len(item) + item + b"\r\n")
                    self.wfile.flush()
                    server._m_anytime_stream_frames.inc()
                    server._m_wire_bytes.inc(len(item), format="binary",
                                             direction="tx")
                    server._costmeter.record_wire(
                        rm.model_id if rm is not None else None, "tx",
                        len(item))
                if pending.error is not None:
                    if headers_sent:
                        # mid-stream failure: tear the stream so the
                        # decoder rejects it (no final frame)
                        self.close_connection = True
                        return
                    self._reply(pending.status_code or 500,
                                json.dumps({"error": pending.error}))
                    return
                if not headers_sent:
                    if pending.response is not None:
                        # downgrade: refinement never engaged, answer the
                        # single payload under its own Content-Type
                        self._reply_explain_ok(pending.response, rm=rm)
                    else:
                        self._reply(500, json.dumps(
                            {"error": "stream produced no frames"}))
                    return
                self.wfile.write(b"0\r\n\r\n")

            def _handle(self):
                register_thread_role("handler")
                # query string split off so /statusz?format=json routes
                # (other routes ignore their query, as before)
                path_only, _, query = self.path.partition("?")
                route = path_only.rstrip("/")
                if route == "/healthz":
                    code, payload = server._health()
                    self._reply(code, json.dumps(payload))
                    return
                if route == "/metrics":
                    self._reply(200, server._render_metrics(),
                                ctype="text/plain; version=0.0.4")
                    return
                if route == "/debugz":
                    # the flight recorder's ring: bounded, thread-safe, the
                    # first artifact to pull when a chaos run goes
                    # sideways — plus the latency histograms' trace
                    # exemplars (bounded, last-K per bucket), so an SLO
                    # breach on /statusz links straight to trace ids
                    payload = server._flight.to_payload()
                    payload["exemplars"] = server.metrics.exemplars()
                    self._reply(200, json.dumps(payload))
                    return
                if route == "/statusz":
                    # the interpreted health page: SLO budgets, alert
                    # states, queue depths, recent timeline (html; stable
                    # JSON schema under ?format=json)
                    ctype, body = statusz_response(
                        server.health, query, detail=server._statusz_detail())
                    self._reply(200, body, ctype=ctype)
                    return
                if route == "/profilez":
                    # the always-on sampler's flamegraph endpoint:
                    # ?format=collapsed|perfetto, ?window=<s> for the
                    # last-60s ring instead of cumulative counts
                    params = urllib.parse.parse_qs(query)
                    ctype, body = contprof().profilez_payload(params)
                    self._reply(200, body, ctype=ctype)
                    return
                if route == "/qualityz":
                    # continuous correctness: audit repro ring, shadow-
                    # oracle error/budget state, canary drift verdicts
                    params = urllib.parse.parse_qs(query)
                    ctype, body = server._quality.qualityz_payload(params)
                    self._reply(200, body, ctype=ctype)
                    return
                if route != "/explain":
                    self._reply(404, json.dumps({"error": "unknown route"}))
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length) or b"{}"
                    req_model_id = None
                    if _wire.is_wire_content_type(
                            self.headers.get("Content-Type")):
                        # binary streaming ingest: one zero-copy
                        # np.frombuffer view straight into the scheduler's
                        # row buffer — no JSON parse, no float-list
                        # re-materialisation
                        req_format = "binary"
                        array, req_model_id = _wire.decode_request_meta(
                            body)
                    else:
                        req_format = "json"
                        payload = json.loads(body)
                        array = np.atleast_2d(
                            np.asarray(payload["array"], dtype=np.float32))
                        if payload.get("model"):
                            req_model_id = str(payload["model"])
                except _wire.WireVersionError as e:
                    # well-formed framing, future protocol: 415 is the
                    # client's downgrade-to-JSON signal
                    self._reply(415, json.dumps({
                        "error": f"unsupported wire version: {e}",
                        "supported_wire_versions": [_wire.WIRE_VERSION]}))
                    return
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    # covers WireError too (truncated header, bad dtype,
                    # torn body): a hostile body is a 400, never a crash
                    self._reply(400, json.dumps({"error": f"bad request: {e}"}))
                    return
                server._m_wire_bytes.inc(len(body), format=req_format,
                                         direction="rx")
                # multi-tenant routing: the X-DKS-Model header wins (the
                # operator-facing knob a proxy can stamp), else the body's
                # model field; resolution pins the ACTIVE version now so a
                # hot-swap mid-flight cannot change this answer.  In
                # single-model mode the field is ignored (pre-registry
                # deployments never spoke it).
                header_model = self.headers.get("X-DKS-Model")
                if header_model:
                    req_model_id = header_model.strip()
                rm = None
                model = server.model
                if server._registry is not None:
                    # pin=True: the in-flight pin is acquired ATOMICALLY
                    # with the lookup, so a concurrent hot-swap's drain
                    # can never observe zero pins between this request
                    # resolving the version and dispatching on it (the
                    # retire path releases the drained version's model)
                    rm = server._registry.resolve(req_model_id, pin=True)
                    if rm is None:
                        self._reply(404, json.dumps({
                            "error": f"unknown model {req_model_id!r}",
                            "models": server._registry.model_ids()}))
                        return
                    model = rm.model
                # tag this handler thread for the sampling profiler: its
                # stacks fold under tenant:<model> (and carry the trace
                # id as an exemplar) for the duration of the request
                prof = contprof()
                prof.tag_current_thread(
                    trace_id=(self.headers.get(_tracing.TRACE_HEADER)
                              or "").split("-")[0] or None,
                    tenant=rm.model_id if rm is not None else None)
                try:
                    self._explain_resolved(array, rm, model, len(body))
                finally:
                    prof.untag_current_thread()
                    if rm is not None:
                        rm.release()

            def _explain_resolved(self, array, rm, model, body_len=0):
                """The /explain path once the tenant (if any) is resolved
                and pinned: negotiation, SLO headers, admission, enqueue,
                reply.  The caller owns releasing the pin."""

                # per-tenant request bytes, attributable only now that
                # routing resolved (the format-labeled fleet counter
                # already moved in _handle)
                server._costmeter.record_wire(
                    rm.model_id if rm is not None else None, "rx", body_len)

                # response negotiation: binary only on an EXPLICIT Accept
                # and only when the served model can encode it — otherwise
                # the historical JSON document (old clients, stub models)
                wire_format = ("binary" if _wire.accepts_wire(
                    self.headers.get("Accept"))
                    and getattr(model, "supports_wire_formats",
                                False) else "json")
                tr = server._tracer
                if tr.enabled:
                    # the request's root span, parented to whatever the
                    # client/proxy minted (X-DKS-Trace); ends in _reply
                    self._dks_root = tr.begin(
                        "server.request",
                        parent=_tracing.parse_trace_header(
                            self.headers.get(_tracing.TRACE_HEADER)),
                        rows=int(array.shape[0]))
                t_admit0 = time.monotonic()
                # chaos harness site: body parsed, nothing dispatched yet
                # (crash/hang/slow before any device work; a drop here is a
                # pre-dispatch connection loss — safe for the proxy to retry)
                if server._faults is not None and \
                        server._faults.fire("server.accept") == "drop":
                    self.close_connection = True
                    return
                # SLO headers (scheduling subsystem): priority class,
                # relative deadline, rate-limit key.  Parsed after the body
                # read so a reject never desyncs the keep-alive connection.
                klass = (self.headers.get("X-DKS-Priority")
                         or server.default_class).strip().lower()
                if klass not in PRIORITY_CLASSES:
                    self._reply(400, json.dumps({
                        "error": f"unknown priority class {klass!r}; "
                                 f"expected one of {list(PRIORITY_CLASSES)}"}))
                    return
                deadline = None
                deadline_ms = self.headers.get("X-DKS-Deadline-Ms")
                if deadline_ms is not None:
                    try:
                        deadline_ms = float(deadline_ms)
                        if not deadline_ms > 0:
                            raise ValueError
                    except ValueError:
                        self._reply(400, json.dumps({
                            "error": "X-DKS-Deadline-Ms must be a positive "
                                     "number of milliseconds"}))
                        return
                    deadline = time.monotonic() + deadline_ms / 1000.0
                # anytime error budget (ISSUE 16): the largest per-feature
                # reported error the client accepts.  Parsed next to the
                # deadline header — the two compose: refinement stops at
                # whichever of {budget met, deadline imminent, schedule
                # exhausted} comes first.
                budget = None
                budget_h = self.headers.get("X-DKS-Error-Budget")
                if budget_h is not None:
                    try:
                        budget = float(budget_h)
                        if not budget > 0:
                            raise ValueError
                    except ValueError:
                        self._reply(400, json.dumps({
                            "error": "X-DKS-Error-Budget must be a "
                                     "positive error bound"}))
                        return
                # streamed partial results: explicit Accept entry AND a
                # deployment that can refine.  A model that cannot refine
                # quietly answers one ordinary (non-stream) response —
                # the client's downgrade path, same as a pre-anytime
                # server.  A budget against a non-refining model is also
                # honest as-is: the full-fidelity answer satisfies every
                # budget.
                can_anytime = (getattr(model, "supports_anytime", False)
                               and getattr(model, "supports_wire_formats",
                                           False))
                stream = (_wire.accepts_stream(self.headers.get("Accept"))
                          and can_anytime)
                anytime_on = can_anytime and (stream or budget is not None)
                client_key = (self.headers.get("X-DKS-Client")
                              or self.client_address[0])
                if server._wedged.is_set():
                    # fast error instead of a socket that hangs until the
                    # pod restart: the reference's crashed-replica requests
                    # failed fast too (connection reset).  Checked AFTER the
                    # body read — an unconsumed body would desync the next
                    # request on this keep-alive connection.
                    self._reply(503, json.dumps({
                        "error": "server wedged: device made no progress "
                                 "within the watchdog timeout"}))
                    return
                max_rows = getattr(model, "max_rows", None)
                if max_rows and array.shape[0] > max_rows:
                    # a single request larger than the model's slot can
                    # never be served; reject IT without failing the batch
                    # it would have been coalesced into
                    self._reply(413, json.dumps({
                        "error": f"request of {array.shape[0]} rows exceeds "
                                 f"this deployment's max_rows={max_rows}"}))
                    return
                root = self.__dict__.get("_dks_root")
                pending = _Pending(array, klass=klass, deadline=deadline,
                                   cache_key=(None if stream else
                                              server._cache_key_for(
                                                  array, wire_format,
                                                  rm=rm)),
                                   trace=root.context if root is not None
                                   else None,
                                   wire_format=wire_format,
                                   model=rm, budget=budget, stream=stream,
                                   anytime_on=anytime_on)
                if anytime_on:
                    # refinement rounds are per-request device state:
                    # never coalesce an anytime pending with anything
                    pending.group_key = ("anytime", id(pending))
                # cache fast path: a duplicate of an already-served request
                # is answered bit-identically without queueing at all —
                # budget-carrying requests accept any stored answer whose
                # fidelity satisfies the budget (keep-best entries)
                if pending.cache_key is not None:
                    cached = server._cache.get(pending.cache_key,
                                               max_err=pending.budget)
                    if cached is not None:
                        server._answer_cached(pending, cached)
                        self._reply_explain_ok(cached, rm=rm)
                        return
                # admission control: shed NOW (429 + Retry-After) rather
                # than letting an unservable request time out in the queue
                # rows_ahead is an O(queue) scan under the scheduler lock;
                # only deadline-carrying requests need the EDF-aware
                # projection (deadline-less ones use queued rows solely
                # for the queue_full Retry-After hint), so the bulk of
                # traffic pays O(1) here
                decision = (server._admission.admit(
                    klass, array.shape[0], client_key, deadline=deadline,
                    queue_depth=server._sched.depths().get(klass, 0),
                    queued_rows=(server._sched.rows_ahead(klass, deadline)
                                 if deadline is not None
                                 else server._sched.queued_rows()))
                    if server._admission is not None else True)
                if not decision:
                    server._shed(decision.reason, rm=rm)
                    retry_s = max(1, int(math.ceil(decision.retry_after_s)))
                    self._reply(429, json.dumps({
                        "error": f"request shed ({decision.reason}); "
                                 f"retry after {decision.retry_after_s:.2f}s",
                        "reason": decision.reason,
                        "retry_after_s": round(decision.retry_after_s, 3)}),
                        headers={"Retry-After": str(retry_s)})
                    return
                if rm is not None:
                    # per-tenant quota (registry/registry.py): a flooding
                    # tenant's token bucket / in-flight bound sheds ITS
                    # requests with 429 while other tenants' admission is
                    # untouched — checked last, like the per-client
                    # bucket, so side-effect-free rejects don't charge it
                    ok, reason, retry = server._registry.admit(
                        rm, exclude_self=True)
                    if not ok:
                        server._shed(reason, rm=rm)
                        self._reply(429, json.dumps({
                            "error": f"request shed ({reason}) for model "
                                     f"{rm.model_id!r}; retry after "
                                     f"{retry:.2f}s",
                            "reason": reason,
                            "retry_after_s": round(retry, 3)}),
                            headers={"Retry-After":
                                     str(max(1, int(math.ceil(retry))))})
                        return
                if root is not None:
                    # header parse + wedge/size checks + admission gates,
                    # i.e. everything between body parse and enqueue
                    tr.record_mono("server.admission", t_admit0,
                                   time.monotonic(), parent=root.context,
                                   klass=klass)
                # (the hot-swap pin was acquired at resolve time and is
                # released by _handle's finally once the reply is sent)
                server._sched.put(pending)
                if pending.stream:
                    self._reply_explain_stream(pending, rm)
                    return
                # re-check shutdown/wedge periodically so in-flight
                # requests fail fast instead of hanging on a dead
                # dispatcher
                while not pending.event.wait(timeout=1.0):
                    if server._stop.is_set():
                        if pending.error is None:
                            pending.error = "server shutting down"
                            pending.status_code = 503
                        break
                    if server._wedged.is_set():
                        # catches requests the watchdog's scheduler
                        # drain can't see (races with next_batch);
                        # claim under the metrics lock so a late
                        # completion can't double-answer
                        with server._metrics_lock:
                            if not pending.done:
                                pending.done = True
                                pending.error = (
                                    "server wedged: device made no "
                                    "progress within the watchdog "
                                    "timeout")
                                # 503 like the watchdog drain: this
                                # request was never dispatched, so a
                                # fan-in proxy can safely fail it over
                                # to a healthy replica (500 would
                                # surface to the client)
                                pending.status_code = 503
                                # this claim bypasses _complete's live
                                # loop, so count it via the shared
                                # helper — error counters matter most
                                # exactly during wedge incidents
                                server._count_request(pending,
                                                      pending.error)
                        if pending.error is not None:
                            break
                if pending.error is not None:
                    self._reply(pending.status_code or 500,
                                json.dumps({"error": pending.error}))
                else:
                    self._reply_explain_ok(pending.response, rm=rm)

            # the reference clients issue GETs with a JSON body
            # (serve_explanations.py:111); accept both verbs
            do_GET = _handle
            do_POST = _handle

            def log_message(self, fmt, *args):
                logger.debug("http: " + fmt, *args)

        return Handler

    # ------------------------------------------------------------------ #

    def start(self):
        # persistent compile cache (env-driven; no-op without
        # DKS_COMPILE_CACHE_DIR): wired before any serving-path compile so
        # warmup + first requests read/write it
        from distributedkernelshap_tpu.runtime.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache()
        # always-on sampling profiler (observability/contprof.py):
        # refcounted — several servers per process share one sampler
        # thread; DKS_CONTPROF=0 leaves it inert
        contprof().acquire()
        self._prof_released = False
        if self._registry is not None and self.model is None:
            # registry mode with no explicit default deployment: the
            # registry's default model anchors depth calibration, staging
            # capability resolution and the single-model fallbacks
            rm0 = self._registry.resolve()
            if rm0 is None:
                raise RuntimeError(
                    "registry mode needs at least one registered model "
                    "before start()")
            self.model = rm0.model
        # bind + serve the socket FIRST: requests arriving during depth
        # calibration park in the scheduler (handlers wait on their response
        # events) instead of getting connection-refused on an unbound port
        self._httpd = _HTTPServer((self.host, self.port), self._make_handler())
        self.port = self._httpd.server_address[1]  # resolve port 0
        t_http = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t_http.start()
        if self.pipeline_depth is None:
            try:
                self.pipeline_depth = calibrate_pipeline_depth(self.model)
            except Exception:
                logger.exception("depth calibration failed; defaulting to 8")
                self.pipeline_depth = 8
        self._inflight = queue.Queue(maxsize=self.pipeline_depth)
        # single-model staging resolves against the model's actual
        # capabilities here: it needs the pipelined path plus the
        # stage_rows hook (serving wrappers), and stage_rows itself may
        # still decline per call (exact/interactions/l1 deployments
        # return None → unstaged path).  Registry mode runs the batcher
        # whenever staging is requested: per-group staging degrades
        # gracefully for tenants without the hooks (staged=None →
        # classic dispatch-time H2D), and a staging-capable tenant
        # registered AFTER start() must get the pipeline too — a
        # roster-at-start capability check would freeze it out.
        if self._registry is not None:
            self._staging_enabled = self._staging_requested
            staging_models = [rm.model
                              for rm in self._registry.active_models()]
        else:
            staging_models = [self.model]
            self._staging_enabled = (
                self._staging_requested
                and hasattr(self.model, "stage_rows")
                and hasattr(self.model, "explain_batch_async"))
            if self._staging_requested and not self._staging_enabled:
                logger.warning(
                    "staging requested but the model exposes no "
                    "stage_rows/explain_batch_async; serving unstaged")
        t_batcher = None
        if self._staging_enabled:
            # one staging slot per active tenant (capped): a cycle's N
            # tenant groups can all be device-resident before the
            # dispatcher needs them, so the batcher never blocks one
            # tenant's upload behind another tenant's compute
            depth = self.staging_depth
            if depth is None:
                depth = (min(4, max(1, len(staging_models)))
                         if self._registry is not None else 1)
            self._staging_slots = depth
            # staged slots pin device buffers between put and get — the
            # ledger charges the staged rows (item[5], falling back to
            # the stacked host array item[4]) under owner=staging
            from distributedkernelshap_tpu.observability.memledger import (
                approx_nbytes,
            )
            self._staged = StagingBuffer(
                depth=depth,
                mem_account=memledger().account("staging"),
                nbytes_fn=lambda item: approx_nbytes(
                    item[5] if item[5] is not None else item[4]))
            t_batcher = threading.Thread(target=self._batcher_loop,
                                         daemon=True)
        t_disp = threading.Thread(target=self._dispatch_loop, daemon=True)
        # one finalizer per pipeline slot (capped: each thread holds a live
        # RPC stream to the device tunnel) so D2H overlap scales with depth
        t_fin = [threading.Thread(target=self._finalize_loop, daemon=True)
                 for _ in range(min(self.pipeline_depth, 8))]
        t_disp.start()
        if t_batcher is not None:
            t_batcher.start()
        for t in t_fin:
            t.start()
        t_dog = threading.Thread(target=self._watchdog_loop, daemon=True)
        t_dog.start()
        # SLO health sampler/alert evaluator (no-op when
        # health_interval_s == 0)
        self.health.start()
        # quality monitor: shadow-oracle drain + periodic canary replay
        self._quality.start()
        self._threads = [t_http, t_disp, t_dog, *t_fin]
        if t_batcher is not None:
            self._threads.append(t_batcher)
        logger.info("ExplainerServer listening on %s:%d/explain (max_batch_size=%d)",
                    self.host, self.port, self.max_batch_size)
        return self

    def stop(self):
        self._stop.set()
        # one-shot: a double stop() must not release another server's
        # profiler reference
        if not getattr(self, "_prof_released", True):
            self._prof_released = True
            contprof().release()
        self.health.stop()
        self._quality.stop()
        self._sched.stop()  # wake the dispatcher's condition wait
        # fail anything still queued — including items deferred for row
        # overflow, which live in the same heap — so no handler thread
        # waits forever and nothing leaks
        for pending in self._sched.drain():
            with self._metrics_lock:
                if pending.done:
                    continue
                pending.done = True
            pending.error = "server shutting down"
            pending.status_code = 503
            pending.event.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve_explainer(predictor, background_data, constructor_kwargs, fit_kwargs,
                    host: str = "0.0.0.0", port: int = 8000,
                    max_batch_size: int = 1, batched: bool = None,
                    pipeline_depth: Optional[int] = None,
                    explain_kwargs: Optional[dict] = None,
                    **server_kwargs) -> ExplainerServer:
    """Build, fit and serve an explainer in one call — the analog of the
    reference's ``backend_setup`` + ``endpont_setup``
    (``serve_explanations.py:27-67``).

    ``pipeline_depth`` is the TPU-native meaning of the reference's replica
    count: how many dispatched batches may be in flight at once (their D2H
    round trips overlap), rather than how many model copies exist.  The
    default (``None``) self-calibrates the depth at startup."""

    from distributedkernelshap_tpu.runtime.compile_cache import (
        enable_persistent_cache,
    )
    from distributedkernelshap_tpu.serving.wrappers import (
        BatchKernelShapModel,
        KernelShapModel,
    )

    # persistent compile cache BEFORE the model build: the explainer fit
    # below compiles too, and a restarted replica should read those
    # executables from the cache as well (start() re-applies for servers
    # constructed around a pre-built model — the call is idempotent)
    enable_persistent_cache()

    cls = BatchKernelShapModel if (batched or max_batch_size > 1) else KernelShapModel
    model = cls(predictor, background_data, constructor_kwargs, fit_kwargs,
                explain_kwargs=explain_kwargs)
    return ExplainerServer(model, host=host, port=port,
                           max_batch_size=max_batch_size,
                           pipeline_depth=pipeline_depth,
                           **server_kwargs).start()
