"""Persistent XLA compile cache + compile-event accounting.

Cold starts dominate the serving stack's tail story: a replica restart
(which the supervisor makes routine) re-pays every first-jit compile from
scratch — ~40-140 s per bucket shape through a tunnelled chip — and the
only mitigation in the tree used to be a grace timer
(``serving/server.py`` ``first_batch_grace_s``).  This module is the
runtime half of the cold-start subsystem:

* :func:`enable_persistent_cache` wires JAX's **persistent compilation
  cache** (``jax_compilation_cache_dir``) from explicit arguments or the
  ``DKS_COMPILE_CACHE_DIR`` / ``DKS_COMPILE_CACHE_MIN_S`` env knobs, and
  degrades to a logged no-op on JAX builds without the config options —
  callers never need to version-gate.
* :func:`compile_events` is the process-wide **compile accountant**: a
  ``jax.monitoring`` listener classifying every backend compile as
  ``fresh`` (XLA actually ran) or ``cache_hit`` (the persistent cache
  served the executable), attributing it to the caller-declared *shape
  signature* (``with compile_events().signature("rows=64"): ...``), and
  exposing the counts/seconds as the ``dks_compile_total`` /
  ``dks_compile_seconds_total`` registry metrics plus ``compile.backend``
  trace spans parented to whatever request/warmup span is ambient.

The classification piggybacks on the event ORDER JAX emits (verified on
0.4.x): a persistent-cache hit records ``/jax/compilation_cache/
cache_hits`` immediately before the ``backend_compile_duration`` event of
the same compile on the same thread, so a thread-local pending-hit flag
pairs them without any private-API reach-in.  On JAX builds without
``jax.monitoring`` the accountant stays inert (zero counts, no errors).
"""

import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

logger = logging.getLogger(__name__)

#: env knobs (documented in docs/PERFORMANCE.md)
CACHE_DIR_ENV = "DKS_COMPILE_CACHE_DIR"
MIN_COMPILE_S_ENV = "DKS_COMPILE_CACHE_MIN_S"

#: suffixes of the jax.monitoring duration events that mark one backend
#: compile (0.4.x spells it without a unit suffix; older/newer builds have
#: carried ``_sec`` variants)
_COMPILE_EVENT_SUFFIXES = ("backend_compile_duration",
                           "backend_compile_duration_sec",
                           "backend_compile_time_sec")
#: the named event a persistent-cache hit records just before the
#: (retrieval-timed) backend_compile event of the same compile
_CACHE_HIT_EVENT = "cache_hits"

_state_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def shape_signature(rows: int, path: Optional[str] = None,
                    model: Optional[str] = None) -> str:
    """The ONE spelling of a declared compile-shape signature
    (``[model=<id>,]rows=<bucket>[,path=<explain path>]``).  Today only
    the warmup ladder declares signatures (live request compiles fold
    into ``_unattributed``); ``path`` distinguishes the exact-TreeSHAP
    entry from the sampled pipeline at the same bucket — they are
    distinct executables, so a ladder that warmed only one of them shows
    up as such in ``dks_compile_total`` instead of hiding behind a shared
    label.  ``model`` is the multi-tenant registry's namespace prefix:
    each registered ``(model_id, version)`` warms its OWN executables, so
    its rungs must be attributable per tenant.  Any future live-dispatch
    attribution must spell its signatures through this helper so the
    labels collide with the matching rung's."""

    sig = f"rows={int(rows)}"
    if path:
        sig = f"{sig},path={path}"
    return sig if not model else f"model={model},{sig}"


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_time_s: Optional[float] = None
                            ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit argument > ``DKS_COMPILE_CACHE_DIR`` env.
    ``None``/empty resolves to "leave JAX's own configuration alone"
    (``JAX_COMPILATION_CACHE_DIR`` still works natively) and returns
    ``None``.  ``min_compile_time_s`` (> ``DKS_COMPILE_CACHE_MIN_S``,
    default 0.0) sets the write threshold — JAX's own default of 1 s
    would skip caching the fast CPU compiles the test/bench environments
    exercise, so the subsystem defaults to caching everything.

    Safe no-op (logged once, returns ``None``) on JAX versions without
    the config options.  Idempotent: re-enabling with the same directory
    does nothing; a different directory re-points the cache.
    """

    global _enabled_dir
    cache_dir = cache_dir or os.environ.get(CACHE_DIR_ENV) or None
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    if min_compile_time_s is None:
        try:
            min_compile_time_s = float(os.environ.get(MIN_COMPILE_S_ENV, "0"))
        except ValueError:
            min_compile_time_s = 0.0
    with _state_lock:
        if _enabled_dir == cache_dir:
            return cache_dir
        try:
            import jax

            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            try:
                jax.config.update("jax_persistent_cache_min_compile_time_secs",
                                  float(min_compile_time_s))
            except AttributeError:  # knob renamed/absent on this JAX
                pass
            try:
                # -1: no entry-size floor — tiny CPU executables must cache
                # too, or the A/B benches would measure nothing
                jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                                  -1)
            except AttributeError:
                pass
            try:
                # the cache singleton latches its directory on the FIRST
                # compile of the process; a server enables the cache only
                # at start(), after the model fit already compiled, so the
                # singleton must be re-pointed or the config update is
                # silently ignored (verified on 0.4.37)
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.reset_cache()
            except (ImportError, AttributeError):
                pass
        except (ImportError, AttributeError, ValueError, OSError) as e:
            # AttributeError/ValueError: JAX without the persistent-cache
            # config; OSError: unwritable dir.  Cold starts then simply
            # stay cold — never break the caller.
            logger.warning("persistent compile cache unavailable (%s); "
                           "continuing without it", e)
            return None
        _enabled_dir = cache_dir
    logger.info("persistent compile cache at %s "
                "(min_compile_time_s=%.3g)", cache_dir, min_compile_time_s)
    return cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The directory :func:`enable_persistent_cache` last applied, if any."""

    with _state_lock:
        return _enabled_dir


class CompileAccounting:
    """Process-wide compile-event counts, by ``(kind, signature)``.

    ``kind`` is ``'fresh'`` (XLA compiled) or ``'cache_hit'`` (persistent
    cache served the executable; the recorded seconds are then retrieval
    time).  ``signature`` is whatever shape label the caller declared via
    :meth:`signature` around the dispatch that may compile — the warmup
    ladder uses ``rows=<bucket>`` — and ``_unattributed`` otherwise.

    Thread-safe; listener registration happens once per process on first
    use (``jax.monitoring`` has no public unregister, and compile truth is
    process-global anyway — per-component registries read it through
    render-time callbacks, see :meth:`metric_counts`).
    """

    def __init__(self):
        self._lock = threading.Lock()
        # {(kind, signature): count}, {(kind, signature): seconds}
        self._counts: Dict[tuple, int] = {}
        self._seconds: Dict[tuple, float] = {}
        # running scalar twin of sum(self._seconds.values()): the cost
        # meter reads it twice per device dispatch, so it must not cost
        # a dict scan
        self._total_s = 0.0
        self._local = threading.local()
        self._listening = False
        self.supported = True

    # -------------------------------------------------------------- #

    def _ensure_listening(self) -> None:
        if self._listening:
            return
        with self._lock:
            if self._listening:
                return
            try:
                import jax.monitoring as monitoring

                monitoring.register_event_listener(self._on_event)
                monitoring.register_event_duration_secs_listener(
                    self._on_duration)
            except Exception as e:  # jax too old / absent: stay inert
                self.supported = False
                logger.warning("compile accounting unavailable "
                               "(jax.monitoring: %s)", e)
            self._listening = True

    def _on_event(self, event: str, **kwargs) -> None:
        if event.rsplit("/", 1)[-1] == _CACHE_HIT_EVENT:
            # pairs with the backend_compile duration event JAX records
            # next on this same thread (the hit's retrieval is timed
            # through the same code path as a real compile)
            self._local.pending_hit = True

    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        name = event.rsplit("/", 1)[-1]
        if name not in _COMPILE_EVENT_SUFFIXES:
            return
        hit = getattr(self._local, "pending_hit", False)
        self._local.pending_hit = False
        kind = "cache_hit" if hit else "fresh"
        sig = getattr(self._local, "signature", None) or "_unattributed"
        key = (kind, sig)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._seconds[key] = self._seconds.get(key, 0.0) + float(duration)
            self._total_s += float(duration)
        self._record_span(kind, sig, duration)

    def _record_span(self, kind: str, sig: str, duration: float) -> None:
        """A ``compile.backend`` trace span for the event, parented to the
        ambient request/warmup context (compiles run synchronously on the
        dispatching thread, so the contextvar is the right parent)."""

        try:
            from distributedkernelshap_tpu.observability import tracing

            tr = tracing.tracer()
            if not tr.enabled:
                return
            end = time.monotonic()
            tr.record_mono("compile.backend", end - duration, end,
                           parent=tracing.current_context(),
                           kind=kind, signature=sig)
        except Exception:  # tracing must never break a compile
            logger.debug("compile span recording failed", exc_info=True)

    # -------------------------------------------------------------- #

    @contextmanager
    def signature(self, sig: str):
        """Attribute compile events fired on THIS thread inside the block
        to shape signature ``sig`` (nesting restores the outer value)."""

        self._ensure_listening()
        prev = getattr(self._local, "signature", None)
        self._local.signature = str(sig)
        try:
            yield self
        finally:
            self._local.signature = prev

    def total_seconds(self) -> float:
        """Cumulative compile seconds across every kind and signature —
        the cost meter's cheap per-dispatch read (an O(1) scalar under
        the lock; ``snapshot()`` copies both dicts and builds per-kind
        totals, too heavy to pay twice per device call)."""

        self._ensure_listening()
        with self._lock:
            return self._total_s

    def snapshot(self) -> Dict[str, Dict]:
        """Structured copy of the counts: ``{"counts": {(kind, sig): n},
        "seconds": {(kind, sig): s}}`` plus per-kind totals."""

        self._ensure_listening()
        with self._lock:
            counts = dict(self._counts)
            seconds = dict(self._seconds)
        totals = {"fresh": 0, "cache_hit": 0}
        sec_totals = {"fresh": 0.0, "cache_hit": 0.0}
        for (kind, _), n in counts.items():
            totals[kind] = totals.get(kind, 0) + n
        for (kind, _), s in seconds.items():
            sec_totals[kind] = sec_totals.get(kind, 0.0) + s
        return {"counts": counts, "seconds": seconds,
                "totals": totals, "seconds_totals": sec_totals}

    @staticmethod
    def delta(before: Dict, after: Dict) -> Dict[str, Dict]:
        """``after - before`` for two :meth:`snapshot` results (new
        signatures appear, untouched ones drop out)."""

        out = {"counts": {}, "seconds": {}}
        for field in ("counts", "seconds"):
            b = before[field]
            for key, val in after[field].items():
                d = val - b.get(key, 0)
                if d:
                    out[field][key] = d
        out["totals"] = {
            k: after["totals"].get(k, 0) - before["totals"].get(k, 0)
            for k in set(after["totals"]) | set(before["totals"])}
        out["seconds_totals"] = {
            k: (after["seconds_totals"].get(k, 0.0)
                - before["seconds_totals"].get(k, 0.0))
            for k in set(after["seconds_totals"])
            | set(before["seconds_totals"])}
        return out

    def fresh_for_signature(self, snapshot_delta: Dict, sig: str) -> int:
        """Fresh-compile count one signature contributed to a delta."""

        return sum(n for (kind, s), n in snapshot_delta["counts"].items()
                   if kind == "fresh" and s == sig)

    # ----------------------- registry callbacks ------------------- #

    def metric_counts(self) -> Dict[tuple, float]:
        self._ensure_listening()
        with self._lock:
            return {k: float(v) for k, v in self._counts.items()}

    def metric_seconds(self) -> Dict[tuple, float]:
        self._ensure_listening()
        with self._lock:
            return dict(self._seconds)

    def attach_metrics(self, registry) -> None:
        """Register ``dks_compile_total{kind,signature}`` and
        ``dks_compile_seconds_total{kind,signature}`` on ``registry`` as
        callback counters reading this (process-global) accountant.
        Signature cardinality is bounded: only warmup-ladder rungs and
        serving buckets declare signatures; everything else folds into
        ``_unattributed``.  Starts the listener immediately — compiles
        fired between registration and the first scrape must count."""

        self._ensure_listening()
        registry.counter(
            "dks_compile_total",
            "Backend compile events by kind (fresh = XLA compiled, "
            "cache_hit = persistent compile cache served the executable) "
            "and declared shape signature.",
            labelnames=("kind", "signature")).set_function(self.metric_counts)
        registry.counter(
            "dks_compile_seconds_total",
            "Seconds spent in backend compile events (cache_hit rows "
            "count retrieval time) by kind and shape signature.",
            labelnames=("kind", "signature")).set_function(
            self.metric_seconds)


_accounting: Optional[CompileAccounting] = None
_accounting_lock = threading.Lock()


def compile_events() -> CompileAccounting:
    """The process-wide compile accountant (created on first use)."""

    global _accounting
    with _accounting_lock:
        if _accounting is None:
            _accounting = CompileAccounting()
        return _accounting
