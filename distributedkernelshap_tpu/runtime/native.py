"""ctypes bindings for the native host-side kernels (see masked_eval.cc).

Builds the shared library on first use with the baked-in g++ toolchain and
caches it next to the sources; every entry point degrades to a numpy
implementation when compilation is unavailable, so the framework never hard-
depends on the native path.
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "masked_eval.cc")
_LIB = os.path.join(_DIR, "libdksruntime.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-march=native",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:
        logger.info("native runtime build failed (%s); using numpy fallback", e)
        return False


def get_lib():
    """Load (building if needed) the native library, or None."""

    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or (
                os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.info("native runtime load failed (%s); using numpy fallback", e)
            return None
        f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
        lib.dks_masked_fill.argtypes = [f32p, f32p, f32p, f32p] + [ctypes.c_int64] * 4
        lib.dks_masked_fill.restype = None
        lib.dks_weighted_mean.argtypes = [f32p, f32p, f32p] + [ctypes.c_int64] * 3
        lib.dks_weighted_mean.restype = None
        _lib = lib
        logger.info("native runtime loaded: %s", _LIB)
        return _lib


def masked_fill(X: np.ndarray, bg: np.ndarray, zc: np.ndarray,
                out: np.ndarray = None) -> np.ndarray:
    """``out[b,s,n,:] = X[b]*zc[s] + bg[n]*(1-zc[s])`` flattened to rows."""

    B, D = X.shape
    N = bg.shape[0]
    S = zc.shape[0]
    if out is None:
        out = np.empty((B * S * N, D), dtype=np.float32)
    lib = get_lib()
    if lib is not None:
        lib.dks_masked_fill(np.ascontiguousarray(X, np.float32),
                            np.ascontiguousarray(bg, np.float32),
                            np.ascontiguousarray(zc, np.float32),
                            out, B, S, N, D)
        return out
    masked = (X[:, None, None, :] * zc[None, :, None, :]
              + bg[None, None, :, :] * (1.0 - zc[None, :, None, :]))
    np.copyto(out, masked.reshape(-1, D).astype(np.float32, copy=False))
    return out


def weighted_mean(pred: np.ndarray, w: np.ndarray, R: int) -> np.ndarray:
    """``ey[r] = Σ_n w[n]·pred[r·N+n]`` for row-major blocks of N rows."""

    N = w.shape[0]
    K = pred.shape[1]
    if pred.shape[0] != R * N:
        raise ValueError(
            f"predictor returned {pred.shape[0]} rows for {R * N} inputs "
            f"(R={R}, N={N}); black-box predictors must preserve row count")
    ey = np.empty((R, K), dtype=np.float32)
    lib = get_lib()
    if lib is not None:
        lib.dks_weighted_mean(np.ascontiguousarray(pred, np.float32),
                              np.ascontiguousarray(w, np.float32), ey, R, N, K)
        return ey
    return np.einsum("rnk,n->rk", pred.reshape(R, N, K), w).astype(np.float32)
