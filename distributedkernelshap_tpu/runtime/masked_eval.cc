// Native host-side data plane for the black-box predictor path.
//
// The reference's native-code surface is Ray's C++ core (object store +
// raylet; SURVEY.md §2.4) shuttling pickled minibatches between actor
// processes.  The TPU build has no object store — its host-side hot loop is
// different: when the predictor is an opaque host callable (XGBoost, pickled
// sklearn pipelines) the synthetic-data tensor  masked[b,s,n,:] =
// x_b ⊙ z_s + bg_n ⊙ (1 - z_s)  must be materialised on the host before
// every predictor call, and the predictor outputs reduced by the background
// weights afterwards.  numpy broadcasts allocate and sweep this B·S·N·D
// tensor twice; these OpenMP kernels build it in one pass and reduce
// without intermediates.
//
// Exposed via ctypes (distributedkernelshap_tpu/runtime/native.py); the
// Python layer falls back to numpy when the shared library is unavailable.

#include <cstdint>

extern "C" {

// out[(b*S + s)*N + n, :] = X[b,:]*zc[s,:] + bg[n,:]*(1 - zc[s,:])
// X: (B, D)  bg: (N, D)  zc: (S, D)  out: (B*S*N, D) preallocated
void dks_masked_fill(const float* X, const float* bg, const float* zc,
                     float* out, int64_t B, int64_t S, int64_t N, int64_t D) {
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t b = 0; b < B; ++b) {
    for (int64_t s = 0; s < S; ++s) {
      const float* x_row = X + b * D;
      const float* z_row = zc + s * D;
      float* block = out + ((b * S + s) * N) * D;
      for (int64_t n = 0; n < N; ++n) {
        const float* bg_row = bg + n * D;
        float* o = block + n * D;
        for (int64_t d = 0; d < D; ++d) {
          const float z = z_row[d];
          o[d] = x_row[d] * z + bg_row[d] * (1.0f - z);
        }
      }
    }
  }
}

// ey[r, k] = sum_n w[n] * pred[r*N + n, k]   (w pre-normalised)
// pred: (R*N, K)  w: (N,)  ey: (R, K) preallocated;  R = B*S
void dks_weighted_mean(const float* pred, const float* w, float* ey,
                       int64_t R, int64_t N, int64_t K) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < R; ++r) {
    const float* block = pred + r * N * K;
    float* out = ey + r * K;
    for (int64_t k = 0; k < K; ++k) out[k] = 0.0f;
    for (int64_t n = 0; n < N; ++n) {
      const float wn = w[n];
      const float* row = block + n * K;
      for (int64_t k = 0; k < K; ++k) out[k] += wn * row[k];
    }
  }
}

}  // extern "C"
