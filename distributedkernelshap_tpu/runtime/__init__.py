from distributedkernelshap_tpu.runtime.native import get_lib, masked_fill, weighted_mean  # noqa: F401
