"""Anytime KernelSHAP: progressive-refinement estimation under an
error-budget contract.

The sampled estimator runs in **rounds** (geometric coalition schedule,
``rounds.py``): every round appends a block of paired coalition draws to
the WLS sufficient statistics accumulated on device (``engine.py`` — the
Gram/moment state carries across rounds, nothing is recomputed), solves
the constrained WLS from the running totals and emits a partial phi plus
a split-half convergence estimate (``convergence.py``, calibrated by
``calibration.py`` against the exact ground-truth paths via the accuracy
bench).  Serving integration (the ``X-DKS-Error-Budget`` header, partial
result streaming, between-round preemption) lives in ``serving/`` and
``scheduling/``; this package is pure estimator machinery.
"""

from distributedkernelshap_tpu.anytime.calibration import (  # noqa: F401
    calibration_factor,
    fit_calibration,
)
from distributedkernelshap_tpu.anytime.convergence import (  # noqa: F401
    monotone_min,
)
from distributedkernelshap_tpu.anytime.rounds import (  # noqa: F401
    RoundSchedule,
    build_schedule,
    round_draw_mask,
)
