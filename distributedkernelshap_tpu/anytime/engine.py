"""The resumable round engine: accumulated WLS sufficient statistics.

Both halves of the constrained WLS normal equations are **sums over
coalition rows** (``ops/explain.normal_equations``), so a round can add
its draw block's contribution to running totals and the solve from the
totals is *the same estimator* as a single-shot solve over the
concatenated rows — the accumulation is a refactor, not a new estimator
(pinned numerically by ``tests/test_anytime.py`` and bit-wise, resumed
vs from-scratch, by ``benchmarks/anytime_bench.py``).

Decomposition (round ``k``, draw scale ``wl = weight_left``):

* ``A(k)   = A_enum + (wl / N_k) * (A_a + A_b)``
* ``rhs(k) = rhs_enum + (wl / N_k) * (rhs_a + rhs_b)``

where ``A_enum`` / ``rhs_enum`` come from the fixed-weight enumerated
block (``A_enum`` is X-independent — a device constant), the ``a`` / ``b``
accumulators sum **unit-count** per-draw statistics split by convergence
stratum, and ``N_k`` is the cumulative draw count — a static per-round
scalar, so round entries stay shape-static and jittable.  Solving each
stratum alone yields the split-half convergence estimate
(``convergence.py``) from state the engine carries anyway.

Per-request state is a flat dict of device arrays (donated through each
round's ``jit_batch_entry``); everything X-independent lives in the
engine's plan-constant cache (``kernel_shap._anytime_consts``).
"""

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributedkernelshap_tpu.anytime.rounds import RoundSchedule
from distributedkernelshap_tpu.ops.explain import (
    _auto_chunk,
    _ey_generic,
    _ey_linear,
    _use_masked_ey,
    record_kernel_path,
    resolve_use_pallas,
    solve_from_normal,
)
from distributedkernelshap_tpu.ops.links import convert_to_link

#: state-dict keys carried across rounds (donated each round)
STATE_KEYS = ("X", "fx", "fx_minus_e", "rhs_enum",
              "A_a", "A_b", "rhs_a", "rhs_b")


def build_ey_fn(predictor, config) -> Callable:
    """``ey(X, bg, bgw_n, mask, G) -> (B, S, K)`` mirroring the kernel
    dispatch of ``ops/explain.build_explainer_fn`` (linear fast path,
    structure-aware masked eval, row-materialising generic), so a round
    block's expected outputs take structurally identical ops to the
    classic single-shot plan's."""

    linear = predictor.linear_decomposition

    def ey(X, bg, bgw_n, mask, G):
        B, D = X.shape
        N = bg.shape[0]
        S, M = mask.shape
        K = predictor.n_outputs
        if linear is not None:
            W, b, activation = linear
            use_pallas = resolve_use_pallas(config.use_pallas)
            record_kernel_path('ey', 'pallas' if use_pallas
                               and activation != 'identity' else 'einsum')
            chunk = config.coalition_chunk or _auto_chunk(
                S, B * N * K, config.target_chunk_elems)
            return _ey_linear(W, b, activation, X, bg, bgw_n, mask, G,
                              chunk, use_pallas=use_pallas)
        if _use_masked_ey(predictor, B, N, S, M, config):
            record_kernel_path('ey', 'masked_ey')
            return predictor.masked_ey(X, bg, bgw_n, mask, G,
                                       config.target_chunk_elems,
                                       coalition_chunk=config.coalition_chunk)
        record_kernel_path('ey', 'generic')
        zc = mask @ G
        chunk = config.coalition_chunk or _auto_chunk(
            S, B * N * D, config.target_chunk_elems)
        return _ey_generic(predictor, X, bg, bgw_n, zc, chunk)

    return ey


def _unit_normal_equations(mask, ey_adj, fx_minus_e):
    """Unit-weight Gram/moment contribution of a draw block — the
    ``w = 1`` specialisation of ``ops/explain.normal_equations`` (counts
    become weights at solve time via the ``wl / N_k`` scale)."""

    zl = mask[:, -1]
    Zt = mask[:, :-1] - zl[:, None]
    A = Zt.T @ Zt
    rhs = jnp.einsum(
        "sm,bsk->bkm", Zt,
        ey_adj - zl[None, :, None] * fx_minus_e[:, None, :])
    return A, rhs


def build_anytime_consts_fn(predictor, config, link: str) -> Callable:
    """Precompute fn for the X-independent anytime constants: device
    copies of background/grouping, the link-space expected value, and the
    enumerated block's weighted Gram matrix + eliminated mask columns.
    Jitted once per engine; results live in the plan-constant cache."""

    link_fn = convert_to_link(link)

    def consts(bg, bgw, enum_mask, enum_w, G):
        with jax.default_matmul_precision(config.matmul_precision):
            bg = jnp.asarray(bg, jnp.float32)
            bgw_n = bgw / jnp.sum(bgw)
            e_out = jnp.einsum("nk,n->k", predictor(bg), bgw_n)
            out = {"bg": bg, "bgw_n": bgw_n, "G": G,
                   "enum_mask": enum_mask,
                   "expected_value": link_fn(e_out)}
            M = enum_mask.shape[1]
            zl = enum_mask[:, -1]
            Zt = enum_mask[:, :-1] - zl[:, None]
            Aw = Zt * enum_w[:, None]
            out.update(zl_enum=zl, Aw_enum=Aw, A_enum=Aw.T @ Zt)
            return out

    return consts


def build_round_fn(predictor, config, link: str, ridge: float,
                   schedule: RoundSchedule, round_idx: int) -> Callable:
    """The round entry for ``round_idx``.

    Round 0: ``(Xp, draw_mask, consts) -> (phi, raw_gap, state)`` —
    evaluates the model on ``Xp``, builds the enumerated block's
    right-hand sides and seeds the stratum accumulators from the first
    draw block.  Later rounds: ``(state, draw_mask, consts) -> ...`` —
    pure accumulation, nothing from earlier rounds is recomputed.  The
    per-round scale factors are baked in as static floats (each round is
    its own trace anyway — the draw-block shape differs).
    """

    link_fn = convert_to_link(link)
    ey_fn = build_ey_fn(predictor, config)
    wl = schedule.weight_left
    n_half = schedule.cumulative_draws(round_idx) / 2.0
    has_enum = schedule.n_enumerated > 0
    M = schedule.M

    def _accumulate(state, draw_mask, consts):
        S = draw_mask.shape[0]
        B = state["X"].shape[0]
        K = predictor.n_outputs
        e_val = consts["expected_value"]
        ey_d = ey_fn(state["X"], consts["bg"], consts["bgw_n"],
                     draw_mask, consts["G"])
        ey_adj = link_fn(ey_d) - e_val[None, None, :]
        # complement-pairs alternate strata in blocks of 4 rows (pair 2t
        # -> stratum a, pair 2t+1 -> stratum b); splitting a PAIR across
        # strata would correlate the halves (rounds.round_draw_mask)
        quads_m = draw_mask.reshape(S // 4, 4, M)
        quads_e = ey_adj.reshape(B, S // 4, 4, K)
        mask_a = quads_m[:, :2].reshape(-1, M)
        mask_b = quads_m[:, 2:].reshape(-1, M)
        ey_a = quads_e[:, :, :2].reshape(B, -1, K)
        ey_b = quads_e[:, :, 2:].reshape(B, -1, K)
        dA_a, drhs_a = _unit_normal_equations(mask_a, ey_a,
                                              state["fx_minus_e"])
        dA_b, drhs_b = _unit_normal_equations(mask_b, ey_b,
                                              state["fx_minus_e"])
        new_state = dict(state)
        new_state.update(A_a=state["A_a"] + dA_a,
                         A_b=state["A_b"] + dA_b,
                         rhs_a=state["rhs_a"] + drhs_a,
                         rhs_b=state["rhs_b"] + drhs_b)
        return new_state

    def _solve(state, consts):
        fx_minus_e = state["fx_minus_e"]
        if has_enum:
            A0, rhs0 = consts["A_enum"], state["rhs_enum"]
        else:
            A0, rhs0 = 0.0, 0.0
        scale = wl / (2.0 * n_half)
        phi = solve_from_normal(
            A0 + scale * (state["A_a"] + state["A_b"]),
            rhs0 + scale * (state["rhs_a"] + state["rhs_b"]),
            fx_minus_e, ridge)
        sa = wl / n_half
        phi_a = solve_from_normal(A0 + sa * state["A_a"],
                                  rhs0 + sa * state["rhs_a"],
                                  fx_minus_e, ridge)
        phi_b = solve_from_normal(A0 + sa * state["A_b"],
                                  rhs0 + sa * state["rhs_b"],
                                  fx_minus_e, ridge)
        raw_gap = 0.5 * jnp.max(jnp.abs(phi_a - phi_b), axis=1)  # (B, M)
        return phi, raw_gap

    if round_idx == 0:
        def round0(Xp, draw_mask, consts):
            with jax.default_matmul_precision(config.matmul_precision):
                X = jnp.asarray(Xp, jnp.float32)
                B = X.shape[0]
                K = predictor.n_outputs
                e_val = consts["expected_value"]
                fx = link_fn(predictor(X))
                fx_minus_e = fx - e_val[None, :]
                if has_enum:
                    ey_e = ey_fn(X, consts["bg"], consts["bgw_n"],
                                 consts["enum_mask"], consts["G"])
                    ey_adj_e = link_fn(ey_e) - e_val[None, None, :]
                    rhs_enum = jnp.einsum(
                        "sm,bsk->bkm", consts["Aw_enum"],
                        ey_adj_e - consts["zl_enum"][None, :, None]
                        * fx_minus_e[:, None, :])
                else:
                    rhs_enum = jnp.zeros((B, K, M - 1), jnp.float32)
                zero_A = jnp.zeros((M - 1, M - 1), jnp.float32)
                zero_rhs = jnp.zeros((B, K, M - 1), jnp.float32)
                state = {"X": X, "fx": fx, "fx_minus_e": fx_minus_e,
                         "rhs_enum": rhs_enum,
                         "A_a": zero_A, "A_b": zero_A,
                         "rhs_a": zero_rhs, "rhs_b": zero_rhs}
                state = _accumulate(state, draw_mask, consts)
                phi, raw_gap = _solve(state, consts)
                return phi, raw_gap, state

        return round0

    def round_k(state, draw_mask, consts):
        with jax.default_matmul_precision(config.matmul_precision):
            state = _accumulate(state, draw_mask, consts)
            phi, raw_gap = _solve(state, consts)
            return phi, raw_gap, state

    return round_k


@dataclass
class RoundResult:
    """One refinement round's outputs, host-side."""

    round_index: int          # 0-based index of the round that just ran
    phi: np.ndarray           # (B, K, M) partial Shapley values
    expected_value: np.ndarray
    raw_prediction: np.ndarray
    est_err: np.ndarray       # (B, M) calibrated, monotone reported error
    raw_gap: np.ndarray       # (B, M) uncalibrated split-half gap
    cumulative_nsamples: int
    done: bool                # schedule exhausted after this round

    @property
    def max_err(self) -> float:
        return float(np.max(self.est_err)) if self.est_err.size else 0.0


@dataclass
class AnytimeRun:
    """Per-request refinement handle.

    Owns the donated device state between rounds; the owning engine's
    ``_dispatch_anytime_round`` drives one round per :meth:`step` call.
    The run object IS the resumable state — a server preempting between
    rounds just re-enqueues the pending that holds it.
    """

    owner: Any                       # KernelExplainerEngine
    schedule: RoundSchedule
    Xp: np.ndarray                   # bucket-padded request rows
    B: int                           # live rows (<= Xp.shape[0])
    round_idx: int = 0
    state: Optional[Dict[str, Any]] = None
    reported_err: Optional[np.ndarray] = None
    expected_value: Optional[np.ndarray] = None
    raw_prediction: Optional[np.ndarray] = None
    last_result: Optional[RoundResult] = None
    last_round_s: float = 0.0
    calibration: Optional[Dict[int, float]] = None

    @property
    def done(self) -> bool:
        return self.round_idx >= self.schedule.n_rounds

    @property
    def rounds_run(self) -> int:
        return self.round_idx

    def step(self) -> RoundResult:
        """Run the next round (blocking) and return its result."""

        if self.done:
            raise RuntimeError("anytime schedule exhausted")
        return self.owner._dispatch_anytime_round(self)

    # ---- resume support (state must survive engine restarts) --------- #

    def export_state(self) -> Dict[str, Any]:
        """Host-side snapshot of the carried state: everything a fresh
        engine needs to continue from ``round_idx`` (used by the resume
        bit-identity check; the serving path keeps the live run)."""

        if self.state is None:
            raise RuntimeError("no state to export before round 0 ran")
        return {
            "round_idx": self.round_idx,
            "B": self.B,
            "Xp": np.asarray(self.Xp),
            "reported_err": None if self.reported_err is None
            else np.asarray(self.reported_err),
            "state": {k: np.asarray(v) for k, v in self.state.items()},
        }

    @classmethod
    def restore(cls, owner, schedule: RoundSchedule,
                snapshot: Dict[str, Any]) -> "AnytimeRun":
        run = cls(owner=owner, schedule=schedule,
                  Xp=snapshot["Xp"], B=int(snapshot["B"]),
                  round_idx=int(snapshot["round_idx"]))
        run.state = {k: jnp.asarray(v)
                     for k, v in snapshot["state"].items()}
        if snapshot.get("reported_err") is not None:
            run.reported_err = np.asarray(snapshot["reported_err"])
        run.raw_prediction = np.asarray(run.state["fx"])[:run.B]
        return run
