"""Round schedules and incremental coalition generation.

The classic plan (``ops/coalitions.coalition_plan``) spends its whole
``nsamples`` budget at once: greedy complete size-pairs, then sampled
draws for the leftover kernel mass.  The anytime schedule splits the SAME
estimator into rounds:

* round 0 carries the **enumerated block** (identical greedy outside-in
  size-pair completion, fixed kernel-mass weights) plus a first block of
  paired sampled draws;
* every later round appends a further block of paired draws, sizes drawn
  from the leftover-mass distribution.

Draw blocks are generated from a per-round seeded Generator
(``SeedSequence((seed, round))``), so round ``r`` is reproducible without
replaying rounds ``0..r-1`` — the resumability contract.  Each block's
row count is a multiple of 4 so complement-pairs split evenly into the
two convergence strata (pairs alternate between strata; splitting a pair
ACROSS strata would correlate the halves and bias the variance estimate
low).  Duplicates are NOT merged inside a block: a repeated row simply
contributes twice to the accumulated Gram/moment sums, which is exactly
the weight accumulation ``coalition_plan``'s dedup performs (counts ARE
weights), without a data-dependent row count.
"""

import hashlib
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from distributedkernelshap_tpu.ops.coalitions import (
    _enumerate_size,
    default_nsamples,
    kernel_size_masses,
)

#: default refinement depth: 4 geometric rounds double the cumulative
#: draw budget per round (the last round lands on the full classic
#: budget, so "schedule exhausted" answers match the fixed-nsamples
#: estimator's sample count)
DEFAULT_ROUNDS = 4
DEFAULT_GROWTH = 2.0

#: smallest per-round draw block (must stay a multiple of 4 — see the
#: strata-split contract above)
MIN_ROUND_DRAWS = 8


def _round4(n: int) -> int:
    return max(MIN_ROUND_DRAWS, 4 * math.ceil(n / 4))


@dataclass(frozen=True)
class RoundSchedule:
    """Static anytime schedule for ``M`` feature groups.

    Attributes
    ----------
    enum_mask / enum_weights
        The round-0 enumerated size-pair block and its fixed kernel-mass
        weights (summing to ``1 - weight_left``); empty arrays when no
        pair fits the round-0 budget.
    weight_left
        Kernel mass carried by the sampled sizes — the scale applied to
        the accumulated unit-count draw statistics.
    sampled_sizes / size_probs
        Non-enumerated subset sizes and their normalised leftover-mass
        draw distribution.
    draws
        Per-round sampled-row counts (paired complements included); each
        a positive multiple of 4.
    """

    M: int
    seed: int
    enum_mask: np.ndarray
    enum_weights: np.ndarray
    weight_left: float
    sampled_sizes: np.ndarray
    size_probs: np.ndarray
    draws: Tuple[int, ...]

    @property
    def n_rounds(self) -> int:
        return len(self.draws)

    @property
    def n_enumerated(self) -> int:
        return int(self.enum_mask.shape[0])

    def cumulative_draws(self, round_idx: int) -> int:
        """Total draw rows accumulated after round ``round_idx`` ran."""

        return int(sum(self.draws[:round_idx + 1]))

    def cumulative_nsamples(self, round_idx: int) -> int:
        return self.n_enumerated + self.cumulative_draws(round_idx)

    def fingerprint(self) -> str:
        """Content fingerprint (mirrors ``plan_fingerprint``): keys the
        device-constant cache, so equal bytes ARE the same constants."""

        cached = self.__dict__.get("_content_fp")
        if cached is not None:
            return cached
        h = hashlib.sha256()
        h.update(repr((self.M, self.seed, self.draws,
                       float(self.weight_left))).encode())
        h.update(np.ascontiguousarray(self.enum_mask).tobytes())
        h.update(np.ascontiguousarray(self.enum_weights).tobytes())
        h.update(np.ascontiguousarray(self.sampled_sizes).tobytes())
        fp = h.hexdigest()
        object.__setattr__(self, "_content_fp", fp)
        return fp


def build_schedule(M: int,
                   nsamples: Optional[int] = None,
                   rounds: int = DEFAULT_ROUNDS,
                   growth: float = DEFAULT_GROWTH,
                   seed: int = 0) -> Optional[RoundSchedule]:
    """Build the anytime round schedule, or ``None`` when refinement
    cannot help: ``M < 2`` (additivity alone determines phi), a budget
    that enumerates every coalition exactly, or a round-0 budget whose
    greedy completion already covers every subset size (no sampled mass
    left to refine)."""

    if M < 2:
        return None
    total = int(nsamples) if nsamples not in (None, "auto") else \
        default_nsamples(M)
    if M <= 62 and 2 ** M - 2 <= total:
        return None

    size_mass = kernel_size_masses(M)
    rounds = max(1, int(rounds))
    # cumulative geometric targets ending exactly on the full budget
    cums = [max(MIN_ROUND_DRAWS,
                int(round(total / growth ** (rounds - r))))
            for r in range(1, rounds + 1)]
    cums[-1] = total

    # greedy outside-in size-pair completion within the round-0 budget —
    # the same loop as coalition_plan, so round 0 IS the classic plan's
    # enumerated block at this budget
    blocks, weights = [], []
    remaining = cums[0]
    weight_left = 1.0
    enumerated_sizes = set()
    for k in range(1, M // 2 + 1):
        pair = [k] if 2 * k == M else [k, M - k]
        count = sum(math.comb(M, s) for s in pair)
        if count > remaining:
            break
        for s in pair:
            rows = _enumerate_size(M, s)
            blocks.append(rows)
            weights.append(np.full(rows.shape[0],
                                   size_mass[s - 1] / rows.shape[0],
                                   dtype=np.float64))
            weight_left -= size_mass[s - 1]
            enumerated_sizes.add(s)
        remaining -= count

    sampled_sizes = np.array(
        [s for s in range(1, M) if s not in enumerated_sizes])
    if sampled_sizes.size == 0 or weight_left <= 0.0:
        return None

    if blocks:
        enum_mask = np.concatenate(blocks, 0).astype(np.float32)
        enum_weights = np.concatenate(weights, 0).astype(np.float32)
    else:
        enum_mask = np.zeros((0, M), dtype=np.float32)
        enum_weights = np.zeros((0,), dtype=np.float32)

    probs = size_mass[sampled_sizes - 1]
    probs = probs / probs.sum()

    n_enum = enum_mask.shape[0]
    draws = [_round4(cums[0] - n_enum)]
    for r in range(1, rounds):
        draws.append(_round4(cums[r] - cums[r - 1]))

    return RoundSchedule(
        M=M, seed=int(seed), enum_mask=enum_mask,
        enum_weights=enum_weights, weight_left=float(weight_left),
        sampled_sizes=sampled_sizes, size_probs=probs,
        draws=tuple(draws))


def round_draw_mask(schedule: RoundSchedule, round_idx: int) -> np.ndarray:
    """The round's ``(draws[round_idx], M)`` 0/1 draw block.

    Paired complements interleaved: pair ``j`` occupies rows ``2j`` and
    ``2j+1``.  Deterministic from ``(seed, round_idx)`` alone — a resumed
    run regenerates round ``r`` without replaying earlier rounds, and a
    from-scratch run at the same schedule produces byte-identical rows.
    """

    if not 0 <= round_idx < schedule.n_rounds:
        raise IndexError(
            f"round {round_idx} outside schedule of {schedule.n_rounds}")
    n = schedule.draws[round_idx]
    M = schedule.M
    rng = np.random.default_rng(
        np.random.SeedSequence((schedule.seed, 0x414E5954, round_idx)))
    n_pairs = n // 2
    sizes = rng.choice(schedule.sampled_sizes, size=n_pairs,
                       p=schedule.size_probs)
    sampled = np.zeros((n_pairs, M), dtype=np.float32)
    for i, s in enumerate(sizes):
        sampled[i, rng.permutation(M)[:s]] = 1.0
    rows = np.empty((n, M), dtype=np.float32)
    rows[0::2] = sampled
    rows[1::2] = 1.0 - sampled
    return rows
