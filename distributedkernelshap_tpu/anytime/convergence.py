"""Split-half convergence estimation for the anytime estimator.

The per-round draw blocks alternate complement-pairs between two strata
(``rounds.round_draw_mask``): stratum A accumulates the even pairs'
Gram/moment sums, stratum B the odd pairs'.  Solving the constrained WLS
from each stratum alone (plus the shared enumerated block) yields two
half-sample phi estimates whose half-gap ``|phi_a - phi_b| / 2``
estimates the sampling error of the pooled estimate — the classic
split-half (2-fold jackknife) variance proxy, computed from statistics
the engine accumulates anyway, so the estimate is device-cheap.

The raw gap is calibrated (``calibration.py``) and reported as a running
minimum across rounds (:func:`monotone_min`): more samples never
*increase* what we claim to know, which is the monotonicity leg of the
serving contract (``benchmarks/anytime_bench.py --check``).
"""

import numpy as np

from distributedkernelshap_tpu.anytime.calibration import (
    ERR_FLOOR,
    calibration_factor,
)


def calibrated_err(raw_gap: np.ndarray, round_idx: int,
                   table=None) -> np.ndarray:
    """Per-feature calibrated error estimate from the raw split-half gap
    (``(B, M)``), floored at :data:`~distributedkernelshap_tpu.anytime.
    calibration.ERR_FLOOR`."""

    factor = calibration_factor(round_idx, table)
    return np.maximum(np.asarray(raw_gap, dtype=np.float32) * factor,
                      ERR_FLOOR).astype(np.float32)


def monotone_min(prev: np.ndarray, cur: np.ndarray) -> np.ndarray:
    """Running minimum of reported error across rounds (``prev`` may be
    ``None`` on the first round)."""

    if prev is None:
        return np.asarray(cur, dtype=np.float32)
    return np.minimum(prev, cur).astype(np.float32)
