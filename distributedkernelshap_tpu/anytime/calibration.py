"""Calibration of the split-half convergence estimate.

The raw split-half gap (``convergence.py``) is an *estimate* of the
estimator's sampling error, not a bound: at small draw counts it can be
optimistic by chance.  The serving contract ("reported error bars bound
true error within x2 at >=90% of rounds", gated by ``make
accuracy-gate``) therefore applies a calibration factor fitted offline
against the exact ground-truth paths (exact-TN / exact-tree / deepshap
via ``benchmarks/estimator_accuracy.py --families anytime``).

The default table was fitted on the accuracy bench's linear/logistic
reference tasks; ``fit_calibration`` re-derives a factor from recorded
``(raw_gap, true_err)`` pairs when the gate detects drift.
"""

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

#: default multiplier applied to the raw split-half gap.  Early rounds
#: carry few draws per stratum, so their gap estimate is noisier — the
#: per-round overrides widen them (fitted offline, see module docstring).
DEFAULT_FACTOR = 4.0

#: per-round-index overrides of :data:`DEFAULT_FACTOR`
DEFAULT_TABLE: Dict[int, float] = {0: 6.0, 1: 5.0}

#: reported error never drops below this floor while draws remain — a
#: zero split-half gap (tiny strata agreeing by chance) must not report
#: certainty the estimator does not have
ERR_FLOOR = 1e-6


def calibration_factor(round_idx: int,
                       table: Optional[Dict[int, float]] = None) -> float:
    """The multiplier for round ``round_idx`` (``table`` overrides the
    default per-round table; missing rounds fall back to
    :data:`DEFAULT_FACTOR`)."""

    t = DEFAULT_TABLE if table is None else table
    return float(t.get(int(round_idx), DEFAULT_FACTOR))


def fit_calibration(pairs: Sequence[Tuple[float, float]],
                    coverage: float = 0.95) -> float:
    """Fit a single calibration factor from ``(raw_gap, true_err)``
    pairs: the smallest multiplier such that ``factor * raw_gap``
    bounds ``true_err`` at the requested coverage quantile.

    Pairs with a zero raw gap are clamped to :data:`ERR_FLOOR` (the same
    floor the runtime applies), so a degenerate gap cannot demand an
    infinite factor."""

    if not pairs:
        return DEFAULT_FACTOR
    ratios = [t / max(r, ERR_FLOOR) for r, t in pairs]
    return float(np.quantile(np.asarray(ratios, dtype=np.float64),
                             min(max(coverage, 0.0), 1.0)))
