"""Grouped-background data containers.

Lightweight equivalents of ``shap.common.Data`` / ``DenseData`` /
``DenseDataWithIndex`` which the reference constructs when feature grouping is
requested (``explainers/kernel_shap.py:581-671``).  They carry the background
matrix together with group names, per-group column indices and optional
per-row weights; the explain engine consumes them directly.
"""

from typing import List, Optional, Sequence

import numpy as np


class Data:
    """Marker base class (parity with ``shap.common.Data``)."""


class DenseData(Data):
    """Dense background data with optional grouping and row weights.

    Parameters
    ----------
    data
        ``(N, D)`` background matrix (rows = samples).
    group_names
        One name per feature group.
    groups
        Per-group column-index lists; defaults to singleton groups (one per
        column, in which case ``len(group_names)`` must equal ``D``).
    weights
        Per-row weights; default uniform.  Normalised to sum to 1.
    """

    def __init__(self,
                 data: np.ndarray,
                 group_names: Sequence[str],
                 groups: Optional[List[Sequence[int]]] = None,
                 weights: Optional[np.ndarray] = None):
        data = np.atleast_2d(np.asarray(data))
        if groups is None:
            groups = [[i] for i in range(data.shape[1])]
        groups = [list(g) for g in groups]

        covered = sorted(i for g in groups for i in g)
        if covered != list(range(data.shape[1])):
            raise ValueError(
                f"groups must partition the {data.shape[1]} data columns; covered {len(covered)}"
            )
        if len(group_names) != len(groups):
            raise ValueError(
                f"Expected {len(groups)} group names, got {len(group_names)}"
            )

        if weights is None:
            weights = np.ones(data.shape[0], dtype=np.float64)
        weights = np.asarray(weights, dtype=np.float64)
        if weights.shape[0] != data.shape[0]:
            raise ValueError(
                f"Expected one weight per background row ({data.shape[0]}), got {weights.shape[0]}"
            )

        self.data = data
        self.group_names = list(group_names)
        self.groups = groups
        self.weights = weights / weights.sum()
        self.transposed = False

    @property
    def group_size(self) -> int:
        return len(self.groups)


class DenseDataWithIndex(DenseData):
    """DenseData carrying a row index (built from indexed DataFrames,
    reference ``kernel_shap.py:638-644``)."""

    def __init__(self, data, group_names, index, index_name, groups=None, weights=None):
        super().__init__(data, group_names, groups=groups, weights=weights)
        self.index = index
        self.index_name = index_name
