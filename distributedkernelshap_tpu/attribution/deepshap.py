"""DeepSHAP/DeepLIFT backprop attribution for lifted neural graphs.

KernelSHAP estimates interventional Shapley values by sampling
coalitions and re-evaluating the model over the synthetic composites —
for a neural predictor that is ``nsamples`` forward passes per instance.
DeepSHAP (Lundberg & Lee 2017's DeepLIFT-as-SHAP formulation; applied to
lifted ONNX graphs by ONNXExplainer, arXiv 2309.16916) rewrites the
computation instead: for each (instance ``x``, background row ``z``)
pair, propagate *multipliers* ``m = Δoutput/Δinput`` from the graph
output back to the input through per-layer rules, and read the
attribution off as ``phi_d = m_d · (x_d - z_d)``.  One forward+backward
pair per background row replaces the whole coalition sweep — no
sampling, no WLS solve.

Layer rules (``attribution rules`` table, docs/PERFORMANCE.md §7):

* **linear rule** — Gemm / MatMul / Add / Conv / AveragePool /
  BatchNormalization (inference = folded affine) / Transpose / Reshape /
  Flatten / Identity: these are affine maps, so the multiplier backprop
  is exactly the transposed linear map — computed with ``jax.vjp`` of
  the node's own evaluation (the bias drops out of the VJP
  automatically, and the same ``_eval_node`` semantics that run the
  forward pass define the backward one, so the two can never disagree).
* **rescale rule** — Relu / Sigmoid / Tanh (elementwise):
  ``m_in = m_out · (f(a_x) - f(a_z)) / (a_x - a_z)``, with the
  elementwise derivative at the midpoint substituted where
  ``|a_x - a_z|`` vanishes (the standard DeepLIFT near-zero guard; the
  limit of the difference quotient).
* **maxpool rule** — MaxPool with non-overlapping windows: the
  multiplier routes to each window's argmax position under ``x``
  (``jax.vjp`` of the pool), rescaled per window by
  ``Δpool_out / Δin[argmax_x]`` so the window's contribution telescopes
  exactly (completeness is preserved window by window).  Overlapping
  windows would double-count the routed positions, so they fail the
  readiness gate instead (``pool_overlap``).

Exactness (asserted against brute-force Shapley enumeration in
``tests/test_deepshap.py`` and ``benchmarks/deepshap_bench.py``):

* **completeness always** — for any supported graph,
  ``Σ_d phi_d = f(x) - Σ_n w_n f(z_n)`` exactly (each rule preserves
  ``Σ m·Δ`` through its layer), which is the additivity the serving
  stack checks end to end;
* **exact Shapley values** when each nonlinearity's input delta is
  feature-separable over the coalition space — in particular (a)
  feature-wise networks (each hidden unit fed by ONE input feature:
  additive models, where the rescale rule IS the Shapley marginal) and
  (b) piecewise-linear nets whose activation pattern is
  coalition-stable for the explained (x, background) pair (the net is
  then linear over the whole coalition cube, e.g. a Conv/Dense/Relu
  stack with non-negative weights, biases and pixels).  Outside those
  regimes DeepSHAP is the standard fast approximation of Shapley
  values, averaged over the background exactly as SHAP's DeepExplainer
  defines it.

The batch entry vmaps instances, ``lax.map``s background rows (one
row's multiplier tensors live at a time — the memory analog of the
coalition-chunked sampled pipeline), contracts the background axis with
the normalised weights in one einsum and folds per-feature phi into
group (e.g. superpixel) phi with a second einsum against the engine's
``(M, D)`` group matrix — the whole thing is ONE jitted program behind
the engine's donated batch entry.

Every reason the path declines a graph-bearing predictor is counted in
``dks_deepshap_fallback_total{reason}`` (mirroring the exact-tree and
exact-TN fallback accounting).
"""

import logging
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributedkernelshap_tpu.registry.onnx_lift import (
    GraphSpec,
    NodeSpec,
    _eval_node,
    _pool_geometry,
    run_graph_reference,
)

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------- #
# Layer-rule table

#: affine maps: multiplier backprop == transposed linear map == VJP
LINEAR_RULE_OPS = frozenset({
    "Gemm", "MatMul", "Add", "Conv", "AveragePool", "BatchNormalization",
    "Transpose", "Reshape", "Flatten", "Identity",
})
#: elementwise nonlinearities: the DeepLIFT rescale rule
RESCALE_RULE_OPS = frozenset({"Relu", "Sigmoid", "Tanh"})
#: windowed max: argmax routing + per-window rescale
POOL_RULE_OPS = frozenset({"MaxPool"})

RULE_COVERED_OPS = LINEAR_RULE_OPS | RESCALE_RULE_OPS | POOL_RULE_OPS

#: |Δin| below this uses the derivative-at-midpoint limit instead of the
#: difference quotient (rescale rule) / zeroes the window ratio (maxpool)
_EPS = 1e-6

#: nominal batch size for the X-independent footprint gate (mirrors
#: ops/tensor_shap._NOMINAL_GATE_B: the gate runs at auto-select time)
_NOMINAL_GATE_B = 256


# ---------------------------------------------------------------------- #
# Fallback accounting (mirrors ops/tensor_shap.py): every reason the
# DeepSHAP path declines a graph-bearing predictor is a metric, not a
# debugging session.

_fallback_lock = threading.Lock()
_fallback_counts: Dict[str, float] = {}
_fallback_logged: set = set()


def record_deepshap_fallback(reason: str, detail: str = "") -> None:
    """Count one DeepSHAP demotion back to the sampled estimator; warn
    on the first occurrence of each reason."""

    with _fallback_lock:
        _fallback_counts[reason] = _fallback_counts.get(reason, 0.0) + 1.0
        first = reason not in _fallback_logged
        if first:
            _fallback_logged.add(reason)
    if first:
        logger.warning(
            "DeepSHAP attribution declined a graph-bearing predictor "
            "(reason=%s%s); counted in dks_deepshap_fallback_total — "
            "further occurrences are counted silently", reason,
            f": {detail}" if detail else "")


def deepshap_fallback_counts() -> Dict[Tuple[str, ...], float]:
    """``{(reason,): count}`` — the registry-callback shape."""

    with _fallback_lock:
        return {(r,): n for r, n in _fallback_counts.items()}


def attach_deepshap_metrics(registry) -> None:
    """Register ``dks_deepshap_fallback_total{reason}`` on ``registry``
    as a callback counter over the process-global fallback accounting."""

    registry.counter(
        "dks_deepshap_fallback_total",
        "DeepSHAP attribution demotion EVENTS back to the sampled "
        "estimator for predictors that carry a lifted neural graph, by "
        "reason (rule = a node outside the layer-rule table, e.g. "
        "Softmax; bilinear = a product node with more than one dynamic "
        "input; pool_overlap = MaxPool windows overlap; link = "
        "non-identity link would change the target quantity; "
        "output_shape = graph output is not (batch, K); footprint = "
        "multiplier tensors exceed the chunk budget; auto_disabled = "
        "DKS_DEEPSHAP_AUTO opt-out).  Counted when the path decision is "
        "made (auto-select / readiness probe), not per served request.",
        labelnames=("reason",)).set_function(deepshap_fallback_counts)


# ---------------------------------------------------------------------- #
# Structure probes and gates


def graph_spec_of(pred) -> Optional[GraphSpec]:
    """The predictor's lifted graph, or ``None``.  Duck-typed on the
    ``graph_spec`` method (``registry/onnx_lift.ONNXPredictor``,
    ``models/cnn.CNNPredictor``) so attribution/ never imports concrete
    model classes at module scope."""

    fn = getattr(pred, "graph_spec", None)
    if fn is None:
        return None
    try:
        spec = fn()
    except Exception:  # a broken structure probe must never crash a path
        logger.debug("graph_spec probe failed", exc_info=True)
        return None
    return spec if isinstance(spec, GraphSpec) else None


def supports_deepshap(pred) -> bool:
    """Whether ``pred`` carries a lifted neural graph whose every node
    has an attribution rule — the structural precondition of the
    DeepSHAP path (gates beyond structure: :func:`deepshap_ready`)."""

    spec = graph_spec_of(pred)
    return (spec is not None
            and all(n.op in RULE_COVERED_OPS for n in spec.nodes))


def _produced_names(spec: GraphSpec) -> set:
    names = {spec.input_name}
    for node in spec.nodes:
        names.update(node.outputs)
    return names


def _structure_reason(spec: GraphSpec) -> Optional[str]:
    """Graph-shape gates shared by readiness and validation: every node
    rule-covered, product nodes single-dynamic, pools non-overlapping."""

    uncovered = sorted({n.op for n in spec.nodes
                        if n.op not in RULE_COVERED_OPS})
    if uncovered:
        return "rule"
    dynamic = _produced_names(spec)
    for node in spec.nodes:
        dyn = [n for n in node.inputs if n in dynamic]
        if node.op in ("Gemm", "MatMul", "Conv") and len(dyn) > 1:
            # a product of two data-dependent tensors is bilinear, not
            # affine — the linear rule's VJP-at-x would be wrong
            return "bilinear"
        if node.op in ("BatchNormalization", "Reshape") \
                and any(n in dynamic for n in node.inputs[1:]):
            # same hole: BN is affine only for CONSTANT scale/mean/var
            # (data-dependent ones make it a product — the linear rule
            # would silently break even completeness), and a Reshape's
            # shape must be a static initializer
            return "bilinear"
        if node.op in POOL_RULE_OPS:
            kernel, strides = _pool_geometry(node)
            if strides[0] < kernel[0] or strides[1] < kernel[1]:
                return "pool_overlap"
    return None


def deepshap_ready(pred, link: str, G=None,
                   target_chunk_elems: Optional[int] = None
                   ) -> Optional[str]:
    """``None`` when the DeepSHAP path can serve this (predictor, link,
    grouping), else the fallback reason string.  Shared by the engine's
    async-readiness probe and the serving auto-selection (which
    additionally records the reason).

    Any 0/1 ``(M, D)`` grouping is accepted: group phi is the sum of the
    member features' phi (the superpixel convention of image SHAP) —
    exact whenever the per-feature phi are, additive always."""

    spec = graph_spec_of(pred)
    if spec is None:
        return "structure"
    try:
        reason = _structure_reason(spec)
    except Exception:
        return "rule"
    if reason is not None:
        return reason
    if link != "identity":
        return "link"
    D = spec.input_dim
    try:
        probe = run_graph_reference(spec, np.zeros((2, D), np.float32))
    except Exception:
        return "rule"
    if probe.ndim != 2 or probe.shape[0] != 2:
        return "output_shape"
    K = int(probe.shape[1])
    if G is not None and np.asarray(G).shape[-1] != D:
        return "grouping"
    # footprint gate: one background row's live multiplier state is
    # ~B×K×D for the input multipliers plus the forward activation pair;
    # bound it by the same chunk budget every other path honours
    budget = target_chunk_elems or (1 << 25)
    if _NOMINAL_GATE_B * max(K, 1) * D * 4 > budget:
        return "footprint"
    return None


def validate_deepshap(pred, link: str, G=None) -> None:
    """Raise with an actionable message when ``nsamples='exact'`` cannot
    run the DeepSHAP backprop for this configuration."""

    reason = deepshap_ready(pred, link, G)
    if reason is None:
        return
    detail = {
        "structure": "the predictor exposes no lifted graph (lift it "
                     "via registry/onnx_lift or models/cnn.graph_spec)",
        "rule": "the graph contains a node outside the attribution rule "
                "table (e.g. Softmax — export the logits head instead)",
        "bilinear": "a Gemm/MatMul/Conv node multiplies two "
                    "data-dependent tensors; the linear rule only "
                    "covers affine maps",
        "pool_overlap": "MaxPool windows overlap (stride < kernel); "
                        "the maxpool rule needs disjoint windows",
        "link": f"link={link!r} would change the target quantity; the "
                "backprop attributes the raw graph output — use "
                "link='identity'",
        "grouping": "the group matrix does not span the graph's input "
                    "features",
        "output_shape": "the graph output is not a (batch, K) tensor",
        "footprint": "the multiplier tensors exceed the chunk budget at "
                     "this (D, K); use the sampled path",
    }[reason]
    raise ValueError(
        f"nsamples='exact' (DeepSHAP backprop) cannot apply: {detail}.")


# ---------------------------------------------------------------------- #
# The multiplier propagation engine


def _split_initializers(spec: GraphSpec):
    """``(float_names, static_vals)``: float-typed initializers are
    traced arguments of the jitted attribution program (they live in the
    engine's content-fingerprint device cache); integer-typed ones
    (Reshape shape vectors) must stay concrete — shapes are static under
    jit."""

    float_names: List[str] = []
    static_vals: Dict[str, np.ndarray] = {}
    for name, arr in spec.initializers.items():
        if np.asarray(arr).dtype.kind == "f":
            float_names.append(name)
        else:
            static_vals[name] = np.asarray(arr)
    return sorted(float_names), static_vals


def _forward_values(spec: GraphSpec, base: dict, X) -> dict:
    """Forward pass recording every edge tensor (the rescale rule needs
    the activation pair at each nonlinearity)."""

    values = dict(base)
    values[spec.input_name] = X
    for node in spec.nodes:
        out = _eval_node(jnp, node, values)
        for name in node.outputs:
            values[name] = out
    return values


def _rescale_ratio(op: str, ax, az):
    """Elementwise ``Δout/Δin`` with the derivative-at-midpoint limit
    where ``|Δin|`` vanishes."""

    if op == "Relu":
        fx, fz = jnp.maximum(ax, 0.0), jnp.maximum(az, 0.0)
        mid_deriv = (0.5 * (ax + az) > 0).astype(ax.dtype)
    elif op == "Sigmoid":
        fx, fz = jax.nn.sigmoid(ax), jax.nn.sigmoid(az)
        s = jax.nn.sigmoid(0.5 * (ax + az))
        mid_deriv = s * (1.0 - s)
    else:  # Tanh
        fx, fz = jnp.tanh(ax), jnp.tanh(az)
        t = jnp.tanh(0.5 * (ax + az))
        mid_deriv = 1.0 - t * t
    din = ax - az
    safe = jnp.where(jnp.abs(din) > _EPS, din, 1.0)
    return jnp.where(jnp.abs(din) > _EPS, (fx - fz) / safe, mid_deriv)


def _accumulate(mult: dict, name: str, m) -> None:
    prev = mult.get(name)
    mult[name] = m if prev is None else prev + m


def _backprop_node(node: NodeSpec, m_out, vx: dict, vz: dict,
                   dynamic: set, mult: dict) -> None:
    """Propagate the output multiplier ``m_out`` (leading K axis over
    graph outputs) of one node onto its dynamic inputs."""

    dyn = [n for n in node.inputs if n in dynamic]
    if not dyn:
        return
    if node.op in RESCALE_RULE_OPS:
        inp = dyn[0]
        ratio = _rescale_ratio(node.op, vx[inp], vz[inp])
        _accumulate(mult, inp, m_out * ratio)
        return
    if node.op in POOL_RULE_OPS:
        inp = dyn[0]
        ax, az = vx[inp], vz[inp]
        diff = ax - az
        kernel, strides = _pool_geometry(node)
        dims, strd = (1, 1) + kernel, (1, 1) + strides

        def maxw(t):
            return jax.lax.reduce_window(t, -jnp.inf, jax.lax.max, dims,
                                         strd, "VALID")

        def sumw(t):
            return jax.lax.reduce_window(t, 0.0, jax.lax.add, dims, strd,
                                         "VALID")

        dout = maxw(ax) - maxw(az)
        # route each window's multiplier to its argmax-|Δin| position
        # (select-and-scatter via the VJP of max over |Δin|), rescaled so
        # the window's contribution telescopes to m_out·Δout exactly.
        # Routing by |Δin| — not by argmax under x — bounds the eps-guard
        # leak: max is 1-Lipschitz in the ∞-norm, so |Δout| ≤ max|Δin|,
        # and a window whose largest |Δin| is ≤ eps carries ≤ eps of
        # Δout (an argmax-under-x route can sit on a Δin of exactly 0 —
        # e.g. Relu clipping both activations — while Δout is large).
        _, vjp_abs = jax.vjp(maxw, jnp.abs(diff))
        sel = vjp_abs(jnp.ones_like(dout))[0]
        din_sel = sumw(sel * diff)
        safe = jnp.where(jnp.abs(din_sel) > _EPS, din_sel, 1.0)
        ratio = jnp.where(jnp.abs(din_sel) > _EPS, dout / safe, 0.0)
        _, vjp_sum = jax.vjp(sumw, diff)  # linear: broadcast to windows
        m_in = jax.vmap(lambda mo: sel * vjp_sum(mo * ratio)[0])(m_out)
        _accumulate(mult, inp, m_in)
        return
    # linear rule: the node is an affine map of its dynamic inputs, so
    # its VJP (which linearises and drops constants) IS the multiplier
    # backprop — evaluated at x, though any point would do
    statics = {n: vx[n] for n in node.inputs if n not in dynamic}

    def node_fn(*dargs):
        local = dict(statics)
        for name, arg in zip(dyn, dargs):
            local[name] = arg
        return _eval_node(jnp, node, local)

    _, vjp_fn = jax.vjp(node_fn, *[vx[n] for n in dyn])
    cots = jax.vmap(vjp_fn)(m_out)
    for name, cot in zip(dyn, cots):
        _accumulate(mult, name, cot)


def _phi_pair(spec: GraphSpec, base: dict, dynamic: set, K: int, x, z):
    """Per-feature attribution ``(K, D)`` of one instance ``x`` against
    one background row ``z``: forward both, propagate multipliers output
    → input through the rule table, read off ``m · (x - z)``."""

    vx = _forward_values(spec, base, x[None])
    vz = _forward_values(spec, base, z[None])
    out = vx[spec.output_name]
    mult = {spec.output_name:
            jnp.eye(K, dtype=out.dtype).reshape(K, 1, K)}
    for node in reversed(spec.nodes):
        m_out = mult.pop(node.outputs[0], None)
        if m_out is None:
            continue  # branch not reaching the explained output
        _backprop_node(node, m_out, vx, vz, dynamic, mult)
    m_in = mult.get(spec.input_name)
    if m_in is None:
        # output independent of the input (constant graph): zero phi
        return jnp.zeros((K, x.shape[0]), out.dtype)
    return m_in[:, 0, :] * (x - z)[None, :]


def build_deepshap_fn(spec: GraphSpec, K: int):
    """Build the jittable batch attribution entry for ``spec``:
    ``fn(X (B, D), params, bg (N, D), bgw_n (N,), G (M, D)) ->
    phi (B, K, M)``.

    ``params`` is the dict of float initializers (the engine serves it
    from its content-fingerprint device cache); integer initializers
    (shape vectors) are baked in as static values.  Instances are
    vmapped, background rows ``lax.map``ped (one row's multiplier
    tensors live at a time), and the weighted background reduction plus
    the feature→group fold are each one einsum."""

    float_names, static_vals = _split_initializers(spec)
    dynamic = _produced_names(spec)

    def phi_fn(X, params, bg, bgw_n, G):
        from distributedkernelshap_tpu.ops.explain import record_kernel_path

        record_kernel_path("exact_phi", "deepshap")
        base = dict(static_vals)
        for name in float_names:
            base[name] = params[name]

        def one_row(z):
            return jax.vmap(
                lambda x: _phi_pair(spec, base, dynamic, K, x, z))(X)

        rows = jax.lax.map(one_row, bg)               # (N, B, K, D)
        feat = jnp.einsum("n,nbkd->bkd", bgw_n, rows)  # (B, K, D)
        return jnp.einsum("bkd,md->bkm", feat, G)      # (B, K, M)

    return phi_fn


# ---------------------------------------------------------------------- #
# Brute-force ground truth (tests / accuracy gate — never a serving path)


def brute_force_shapley(host_fn, x: np.ndarray, bg: np.ndarray,
                        bgw: Optional[np.ndarray] = None,
                        G: Optional[np.ndarray] = None) -> np.ndarray:
    """Exact interventional Shapley values ``(K, M)`` for ONE instance by
    full ``2^M`` coalition enumeration — the ground-truth oracle the
    DeepSHAP exactness claims are asserted against.  ``host_fn`` is a
    host ``(n, D) -> (n, K)`` callable; ``G`` the 0/1 ``(M, D)`` group
    matrix (identity when omitted).  Float64 accumulation; refuses
    M > 16 (65536 composites × N background rows is the ceiling of
    'cheap oracle')."""

    x = np.asarray(x, np.float64).reshape(-1)
    bg = np.atleast_2d(np.asarray(bg, np.float64))
    D = x.shape[0]
    G = np.eye(D) if G is None else np.asarray(G, np.float64)
    M = G.shape[0]
    if M > 16:
        raise ValueError(f"brute force is 2^M; M={M} is past the oracle "
                         "ceiling of 16")
    N = bg.shape[0]
    w = (np.ones(N) if bgw is None else np.asarray(bgw, np.float64))
    w = w / w.sum()

    n_coal = 1 << M
    masks = ((np.arange(n_coal)[:, None] >> np.arange(M)[None, :]) & 1
             ).astype(np.float64)                     # (2^M, M)
    cols = np.clip(masks @ G, 0.0, 1.0)               # (2^M, D)
    # composite rows: coalition features from x, the rest from each bg row
    rows = (cols[:, None, :] * x[None, None, :]
            + (1.0 - cols)[:, None, :] * bg[None, :, :])  # (2^M, N, D)
    fx = np.asarray(host_fn(rows.reshape(-1, D).astype(np.float32)),
                    np.float64)
    K = fx.shape[1] if fx.ndim > 1 else 1
    v = (fx.reshape(n_coal, N, K) * w[None, :, None]).sum(1)  # (2^M, K)

    from math import factorial

    fM = factorial(M)
    size_w = np.array([factorial(s) * factorial(M - 1 - s) / fM
                       for s in range(M)])
    sizes = masks.sum(1).astype(int)                  # (2^M,)
    phi = np.zeros((K, M))
    for m in range(M):
        without = masks[:, m] == 0
        idx = np.nonzero(without)[0]
        with_m = idx | (1 << m)                       # S ∪ {m}
        wgt = size_w[sizes[idx]]
        phi[:, m] = ((v[with_m] - v[idx]) * wgt[:, None]).sum(0)
    return phi
