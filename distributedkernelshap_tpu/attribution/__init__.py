"""Deep-model attribution engine (DeepSHAP/DeepLIFT backprop).

The sampled KernelSHAP estimator treats every predictor as a black box;
for lifted neural graphs the graph ITSELF is the cheaper explainer:
propagating DeepLIFT multipliers from output to input costs one
forward+backward pair per (instance, background row) instead of
``nsamples`` forward passes over synthetic coalitions (ONNXExplainer,
arXiv 2309.16916).  ``attribution/deepshap.py`` implements the layer-rule
engine over ``registry/onnx_lift.GraphSpec`` graphs; the serving stack
promotes it to a first-class engine path (``path="deepshap"``) alongside
linear / exact_tree / exact_tn.
"""

from distributedkernelshap_tpu.attribution.deepshap import (  # noqa: F401
    attach_deepshap_metrics,
    brute_force_shapley,
    build_deepshap_fn,
    deepshap_fallback_counts,
    deepshap_ready,
    record_deepshap_fallback,
    supports_deepshap,
    validate_deepshap,
)
