"""distributedkernelshap_tpu — TPU-native distributed KernelSHAP.

A from-scratch JAX/XLA re-design of the capabilities of
alexcoca/DistributedKernelShap: the per-instance Python hot loop of
``shap.KernelExplainer`` becomes a jit+vmap'd XLA pipeline (coalition
sampling, masked synthetic evaluation, constrained weighted-least-squares
solve), and the Ray actor-pool / Ray Serve orchestration becomes sharded
computation over a ``jax.sharding.Mesh`` with XLA collectives over ICI/DCN.
"""

from distributedkernelshap_tpu.interface import (  # noqa: F401
    DEFAULT_DATA_KERNEL_SHAP,
    DEFAULT_META_KERNEL_SHAP,
    Explainer,
    Explanation,
    FitMixin,
    NumpyEncoder,
)
from distributedkernelshap_tpu.utils import Bunch, batch, get_filename, methdispatch  # noqa: F401
from distributedkernelshap_tpu.data import Data, DenseData, DenseDataWithIndex  # noqa: F401
from distributedkernelshap_tpu.kernel_shap import (  # noqa: F401
    DISTRIBUTED_OPTS,
    KERNEL_SHAP_BACKGROUND_THRESHOLD,
    KERNEL_SHAP_PARAMS,
    KernelExplainerEngine,
    KernelShap,
    rank_by_importance,
    rank_interaction_pairs,
    sum_categories,
)

__version__ = "0.1.0"
