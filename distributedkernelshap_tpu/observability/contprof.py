"""Continuous sampling profiler: always-on, role- and tenant-tagged
folded stacks served on ``/profilez``.

Spans (``observability/tracing.py``) see only what was instrumented;
phase timers (``profiling.py``) see only the engine's named phases.
Everything else a serving host spends wall time on — GIL contention,
JSON encode, scheduler scans, socket writes — is invisible to both,
exactly the host-side plumbing cost Podracer found dominating these
architectures (PAPERS.md, arXiv 2104.06272).  A sampling profiler needs
no instrumentation: a daemon thread walks ``sys._current_frames()`` at
a low default rate (:data:`DEFAULT_HZ` = 19 Hz — prime, so it cannot
alias against the 1 s tick threads) and folds each thread's stack into
a bounded table, both cumulative and a last-60 s ring of per-second
buckets.

Each sample is tagged with the sampled thread's **role** — the serving
loops register themselves at spawn (``dispatcher``/``batcher``/
``finalizer``/``handler``/``tick``; unregistered threads fold under
``other``) — and, where the serving layer published the request context
for the thread, the active **tenant** (part of the fold key) and trace
id (kept as a per-stack exemplar, NOT part of the key — trace ids churn
per request and would unbound the table).  A sampler cannot read
another thread's thread-locals, so the server publishes
(ident -> tenant/trace) into the profiler at request adoption points.

Exports: collapsed-stack text (``frame;frame;frame count`` — the
flamegraph.pl / speedscope wire format, merged across replicas by the
proxy's ``/profilez?federate=1`` over its concurrent scrape pool) and a
Perfetto-compatible chrome-trace JSON whose events round-trip through
:func:`from_perfetto`.

The profiler meters itself (``dks_prof_samples_total``,
``dks_prof_overhead_seconds_total``, ``dks_prof_dropped_stacks_total``)
and **auto-disables** when its own sweep time exceeds a configured
fraction of wall time (:data:`DEFAULT_OVERHEAD_BUDGET`) — an observer
that starts costing real latency turns itself off and says so, rather
than taxing the fleet it watches.  ``DKS_CONTPROF=0|1|<hz>`` (default:
on at 19 Hz).

Stdlib-only, like the rest of the observability package.
"""

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from distributedkernelshap_tpu.analysis.lockwitness import make_lock

logger = logging.getLogger(__name__)

#: default sampling rate — prime, to avoid aliasing with 1 s tickers
DEFAULT_HZ = 19.0

#: bound on distinct (role, tenant, stack) fold keys; overflow counts
#: into dks_prof_dropped_stacks_total instead of growing the table
DEFAULT_MAX_STACKS = 2048

#: auto-disable when sweep time exceeds this fraction of wall time
DEFAULT_OVERHEAD_BUDGET = 0.02

#: frames kept per stack (deepest retained; pathological recursion must
#: not make one sample arbitrarily expensive)
MAX_STACK_DEPTH = 64

#: seconds of per-second ring buckets behind the windowed view
WINDOW_S = 60


def resolve_contprof_env(default_hz: float = DEFAULT_HZ) -> float:
    """``DKS_CONTPROF=0|1|<hz>`` -> sampling rate in Hz (0 = off).
    Unset means on at the low default rate; garbage parses as the
    default, loudly."""

    raw = os.environ.get("DKS_CONTPROF")
    if raw is None or raw.strip() == "":
        return default_hz
    val = raw.strip().lower()
    if val in ("0", "false", "off", "no"):
        return 0.0
    if val in ("1", "true", "on", "yes"):
        return default_hz
    try:
        hz = float(val)
    except ValueError:
        logger.warning("DKS_CONTPROF=%r is not 0|1|<hz>; using %.1f Hz",
                       raw, default_hz)
        return default_hz
    return max(0.0, min(hz, 250.0))


def _fold_frame(frame, max_depth: int = MAX_STACK_DEPTH
                ) -> Tuple[str, ...]:
    """Root-first tuple of ``module:function`` frames."""

    out: List[str] = []
    while frame is not None and len(out) < max_depth:
        code = frame.f_code
        fname = os.path.basename(code.co_filename)
        if fname.endswith(".py"):
            fname = fname[:-3]
        out.append(f"{fname}:{code.co_name}")
        frame = frame.f_back
    out.reverse()
    return tuple(out)


def _stack_line(role: str, tenant: str, stack: Tuple[str, ...]) -> str:
    """One collapsed line's stack part: role (and tenant, when tagged)
    lead as synthetic root frames so flamegraphs split by them."""

    prefix = [f"thread:{role}"]
    if tenant:
        prefix.append(f"tenant:{tenant}")
    return ";".join(prefix + list(stack))


def parse_collapsed(text: str) -> Dict[str, int]:
    """``{stack_line: count}`` from collapsed text (duplicates sum)."""

    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            n = int(count)
        except ValueError:
            continue
        out[stack] = out.get(stack, 0) + n
    return out


def merge_collapsed(pages: Iterable[str]) -> str:
    """Sum-merge collapsed pages (the proxy's federated flamegraph)."""

    merged: Dict[str, int] = {}
    for page in pages:
        for stack, n in parse_collapsed(page).items():
            merged[stack] = merged.get(stack, 0) + n
    return "\n".join(f"{stack} {n}"
                     for stack, n in sorted(merged.items())) \
        + ("\n" if merged else "")


def from_perfetto(doc: Dict) -> Dict[str, int]:
    """Rebuild ``{stack_line: count}`` from :meth:`ContProf.perfetto`
    output — the round-trip contract the export tests pin."""

    out: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        args = ev.get("args") or {}
        stack = args.get("stack")
        if stack is None:
            continue
        line = _stack_line(args.get("role", "other"),
                           args.get("tenant", ""), tuple(stack))
        out[line] = out.get(line, 0) + int(args.get("count", 0))
    return out


class ContProf:
    """The sampling profiler (see module doc).  One instance runs one
    daemon sampler thread; the process-wide instance behind
    :func:`contprof` is refcounted by the serving components
    (:meth:`acquire`/:meth:`release`)."""

    def __init__(self, hz: Optional[float] = None,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 overhead_budget: float = DEFAULT_OVERHEAD_BUDGET):
        self.hz = resolve_contprof_env() if hz is None else float(hz)
        self.max_stacks = int(max_stacks)
        self.overhead_budget = float(overhead_budget)
        #: master switch: sweeps no-op while False (cheap pause — the
        #: bench's on/off alternation flips this per request)
        self.enabled = self.hz > 0
        self._lock = make_lock("contprof.table")
        self._roles: Dict[int, str] = {}
        self._tags: Dict[int, Dict[str, str]] = {}
        # fold key (role, tenant, stack) -> cumulative count
        self._cum: Dict[Tuple[str, str, Tuple[str, ...]], int] = {}
        # per-stack trace exemplar: last trace id seen on a tagged
        # thread sampled at this key (bounded by the fold-table bound)
        self._trace_exemplars: Dict[Tuple, str] = {}
        # ring of (epoch second, {fold key: count})
        self._ring: "deque[Tuple[int, Dict]]" = deque(maxlen=WINDOW_S)
        self._samples_total = 0
        self._sweeps_total = 0
        self._dropped = 0
        self._overhead_s = 0.0
        self._auto_disabled = False
        self._started_mono: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._refs = 0
        self._ref_lock = make_lock("contprof.refs")

    # -- lifecycle ------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def auto_disabled(self) -> bool:
        with self._lock:
            return self._auto_disabled

    def start(self) -> "ContProf":
        """Start the sampler thread (idempotent; no-op at hz<=0)."""

        if self.hz <= 0 or self.running:
            return self
        self._stop.clear()
        with self._lock:
            self._auto_disabled = False
        self._started_mono = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="dks-contprof", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def acquire(self) -> "ContProf":
        """Refcounted start: each serving component (server, proxy)
        acquires on start and releases on stop; the shared sampler runs
        while anyone holds it."""

        with self._ref_lock:
            self._refs += 1
        self.start()
        return self

    def release(self) -> None:
        with self._ref_lock:
            self._refs = max(0, self._refs - 1)
            last = self._refs == 0
        if last:
            self.stop()

    def pause(self) -> None:
        """Keep the thread, skip the work (per-request overhead arms)."""

        with self._lock:
            self.enabled = False

    def resume(self) -> None:
        with self._lock:
            self.enabled = True

    # -- per-thread registration (cheap: one dict write) ---------------

    def register_current_thread(self, role: str) -> None:
        ident = threading.get_ident()
        if self._roles.get(ident) != role:
            with self._lock:
                self._roles[ident] = role

    def tag_current_thread(self, trace_id: Optional[str] = None,
                           tenant: Optional[str] = None) -> None:
        """Publish the calling thread's request context for the sampler
        (merges non-None fields into the existing tag)."""

        ident = threading.get_ident()
        with self._lock:
            tag = self._tags.setdefault(ident, {})
            if trace_id is not None:
                tag["trace"] = str(trace_id)
            if tenant is not None:
                tag["tenant"] = str(tenant)

    def untag_current_thread(self) -> None:
        ident = threading.get_ident()
        with self._lock:
            self._tags.pop(ident, None)

    # -- the sampler ----------------------------------------------------

    def _run(self) -> None:
        interval = 1.0 / self.hz
        # guarded tick (the prober's DKS-C005 pattern): one bad sweep is
        # logged, the sampler survives — an observer must not die of a
        # transient introspection error
        while not self._stop.wait(interval):
            try:
                self._sweep()
            except Exception:
                logger.exception("contprof sweep failed")

    def _sweep(self) -> None:
        with self._lock:
            if not self.enabled or self._auto_disabled:
                return
        t0 = time.perf_counter()
        own = threading.get_ident()
        frames = sys._current_frames()
        second = int(time.monotonic())
        with self._lock:
            if self._ring and self._ring[-1][0] == second:
                bucket = self._ring[-1][1]
            else:
                bucket = {}
                self._ring.append((second, bucket))
            for ident, frame in frames.items():
                if ident == own:
                    continue
                role = self._roles.get(ident, "other")
                tag = self._tags.get(ident)
                tenant = tag.get("tenant", "") if tag else ""
                stack = _fold_frame(frame)
                key = (role, tenant, stack)
                if key not in self._cum \
                        and len(self._cum) >= self.max_stacks:
                    self._dropped += 1
                    continue
                self._cum[key] = self._cum.get(key, 0) + 1
                bucket[key] = bucket.get(key, 0) + 1
                self._samples_total += 1
                if tag and tag.get("trace"):
                    self._trace_exemplars[key] = tag["trace"]
            # dead threads keep no role/tag entries
            for d in (self._roles, self._tags):
                for ident in [i for i in d if i not in frames]:
                    d.pop(ident, None)
            self._sweeps_total += 1
            self._overhead_s += time.perf_counter() - t0
            overhead = self._overhead_s
        started = self._started_mono
        elapsed = (time.monotonic() - started) if started else 0.0
        if elapsed > 1.0 and overhead / elapsed > self.overhead_budget:
            with self._lock:
                self._auto_disabled = True
            logger.warning(
                "contprof auto-disabled: sweep overhead %.2f%% of wall "
                "time exceeds the %.2f%% budget (%.0f Hz over %d "
                "threads) — lower DKS_CONTPROF or raise the budget",
                100.0 * overhead / elapsed,
                100.0 * self.overhead_budget, self.hz, len(frames))

    # -- views / exports ------------------------------------------------

    def _counts(self, window_s: Optional[float] = None) -> Dict:
        with self._lock:
            if window_s is None:
                return dict(self._cum)
            cutoff = int(time.monotonic()) - int(window_s)
            out: Dict = {}
            for second, bucket in self._ring:
                if second < cutoff:
                    continue
                for key, n in bucket.items():
                    out[key] = out.get(key, 0) + n
            return out

    def collapsed(self, window_s: Optional[float] = None) -> str:
        """Collapsed-stack text, cumulative or windowed."""

        counts = self._counts(window_s)
        lines = [f"{_stack_line(role, tenant, stack)} {n}"
                 for (role, tenant, stack), n in counts.items()]
        return "\n".join(sorted(lines)) + ("\n" if lines else "")

    def perfetto(self, window_s: Optional[float] = None) -> Dict:
        """Chrome-trace JSON (Perfetto-loadable): one ``X`` slice per
        fold key, duration proportional to its sample count, one track
        per role.  ``args`` carry the exact fold key so
        :func:`from_perfetto` round-trips."""

        counts = self._counts(window_s)
        roles = sorted({role for role, _, _ in counts})
        tid = {role: i + 1 for i, role in enumerate(roles)}
        events: List[Dict] = []
        for role in roles:
            events.append({"ph": "M", "pid": 1, "tid": tid[role],
                           "name": "thread_name",
                           "args": {"name": f"role:{role}"}})
        with self._lock:
            exemplars = dict(self._trace_exemplars)
        cursors = {role: 0 for role in roles}
        for (role, tenant, stack), n in sorted(counts.items()):
            args = {"stack": list(stack), "role": role,
                    "tenant": tenant, "count": n}
            trace = exemplars.get((role, tenant, stack))
            if trace:
                args["trace_id"] = trace
            events.append({
                "ph": "X", "pid": 1, "tid": tid[role], "cat": "contprof",
                "name": stack[-1] if stack else "<idle>",
                "ts": cursors[role], "dur": n * 1000, "args": args,
            })
            cursors[role] += n * 1000
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"source": "dks-contprof", "hz": self.hz}}

    def stats(self) -> Dict:
        with self._lock:
            window_samples = sum(sum(b.values()) for _, b in self._ring)
            role_counts: Dict[str, int] = {}
            for role in self._roles.values():
                role_counts[role] = role_counts.get(role, 0) + 1
            return {
                "enabled": self.enabled,
                "running": self.running,
                "auto_disabled": self._auto_disabled,
                "hz": self.hz,
                "samples_total": self._samples_total,
                "sweeps_total": self._sweeps_total,
                "dropped_stacks": self._dropped,
                "overhead_seconds": self._overhead_s,
                "distinct_stacks": len(self._cum),
                "window_samples": window_samples,
                "registered_roles": role_counts,
            }

    def status_doc(self, top_n: int = 20) -> Dict:
        counts = self._counts(None)
        top = sorted(counts.items(), key=lambda kv: -kv[1])[:top_n]
        doc = self.stats()
        doc["top_stacks"] = [
            [_stack_line(role, tenant, stack), n]
            for (role, tenant, stack), n in top]
        return doc

    def reset(self) -> None:
        with self._lock:
            self._cum.clear()
            self._ring.clear()
            self._trace_exemplars.clear()
            self._samples_total = 0
            self._sweeps_total = 0
            self._dropped = 0
            self._overhead_s = 0.0
            self._auto_disabled = False
            self._started_mono = time.monotonic()

    def samples_total(self) -> int:
        with self._lock:
            return self._samples_total

    # -- serving --------------------------------------------------------

    def profilez_payload(self, query_params: Dict[str, List[str]]
                         ) -> Tuple[str, bytes]:
        """``(content_type, body)`` for ``GET /profilez`` — shared by
        the server and proxy handlers.  ``format=collapsed|perfetto``
        (default: a JSON status doc with the top stacks);
        ``window=<seconds>`` restricts either export to the ring."""

        fmt = (query_params.get("format") or [""])[-1]
        window = None
        raw_window = (query_params.get("window") or [""])[-1]
        if raw_window:
            try:
                window = max(0.0, float(raw_window))
            except ValueError:
                window = None
        if fmt == "collapsed":
            return ("text/plain; charset=utf-8",
                    self.collapsed(window).encode())
        if fmt == "perfetto":
            return ("application/json",
                    json.dumps(self.perfetto(window)).encode())
        return ("application/json",
                json.dumps(self.status_doc()).encode())

    def attach_metrics(self, registry) -> None:
        """Self-metering families (callback-sourced; both the server's
        and the proxy's registry may read the process profiler)."""

        registry.counter(
            "dks_prof_samples_total",
            "Thread stack samples folded by the continuous sampling "
            "profiler (one per live thread per sweep).").set_function(
                lambda: float(self._samples_total))
        registry.counter(
            "dks_prof_overhead_seconds_total",
            "Wall seconds the profiler spent inside its own sweeps — "
            "the numerator of the auto-disable budget "
            "(overhead/elapsed > budget turns the sampler off)."
        ).set_function(lambda: float(self._overhead_s))
        registry.counter(
            "dks_prof_dropped_stacks_total",
            "Samples dropped because the fold table hit its distinct-"
            "stack bound — the table is bounded by design; a nonzero "
            "value means the profile under-counts rare stacks."
        ).set_function(lambda: float(self._dropped))


_default: Optional[ContProf] = None
_default_lock = make_lock("contprof.singleton")


def contprof() -> ContProf:
    """The process-wide profiler (created on first use, honoring
    ``DKS_CONTPROF``)."""

    global _default
    with _default_lock:
        if _default is None:
            _default = ContProf()
        return _default


def register_thread_role(role: str) -> None:
    """Module-level convenience for thread loops: register the calling
    thread's role with the process profiler."""

    contprof().register_current_thread(role)
