"""End-to-end observability for the serving + pool stack.

Three pieces, all stdlib-only (nothing here may import jax/numpy — the
fan-in proxy and the replica workers import this before the heavyweight
stack comes up):

* :mod:`~distributedkernelshap_tpu.observability.metrics` — the central
  thread-safe metrics registry (Counter/Gauge/Histogram with labels) and
  the ONE Prometheus text renderer every ``/metrics`` endpoint uses,
  plus the exposition-format parser/validator behind the compliance test
  and ``make obs-check``;
* :mod:`~distributedkernelshap_tpu.observability.tracing` — spans with
  W3C-style context propagation over ``X-DKS-Trace``, a bounded ring
  buffer, JSONL export and a Chrome/Perfetto ``trace_event`` converter;
* :mod:`~distributedkernelshap_tpu.observability.flightrec` — a flight
  recorder: the last N structured events (sheds, hedges, restarts,
  journal invalidations, wedges, fault injections), queryable at
  ``/debugz`` and dumped to disk on an injected crash.

See ``docs/OBSERVABILITY.md`` for the metric catalog, trace header
format, ``/debugz`` schema and Perfetto how-to.
"""

# NOTE: the ``flightrec()`` accessor function is deliberately NOT
# re-exported here — it shares its name with its submodule, and binding it
# on the package would shadow ``observability.flightrec`` for module-path
# imports.  Import it from the submodule:
# ``from distributedkernelshap_tpu.observability.flightrec import flightrec``.
from distributedkernelshap_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder,
)
from distributedkernelshap_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    parse_exposition,
    validate_exposition,
)
from distributedkernelshap_tpu.observability.tracing import (  # noqa: F401
    TRACE_HEADER,
    Span,
    SpanContext,
    Tracer,
    current_context,
    format_trace_header,
    parse_trace_header,
    tracer,
    use_context,
)
