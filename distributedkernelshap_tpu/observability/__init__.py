"""End-to-end observability for the serving + pool stack.

Three pieces, all stdlib-only (nothing here may import jax/numpy — the
fan-in proxy and the replica workers import this before the heavyweight
stack comes up):

* :mod:`~distributedkernelshap_tpu.observability.metrics` — the central
  thread-safe metrics registry (Counter/Gauge/Histogram with labels) and
  the ONE Prometheus text renderer every ``/metrics`` endpoint uses,
  plus the exposition-format parser/validator behind the compliance test
  and ``make obs-check``;
* :mod:`~distributedkernelshap_tpu.observability.tracing` — spans with
  W3C-style context propagation over ``X-DKS-Trace``, a bounded ring
  buffer, JSONL export and a Chrome/Perfetto ``trace_event`` converter;
* :mod:`~distributedkernelshap_tpu.observability.flightrec` — a flight
  recorder: the last N structured events (sheds, hedges, restarts,
  journal invalidations, wedges, fault injections), queryable at
  ``/debugz`` and dumped to disk on an injected crash;
* :mod:`~distributedkernelshap_tpu.observability.timeseries` — a bounded
  in-process time-series store (fixed-interval ring per series) fed by a
  background sampler over the live registries, with windowed ``rate`` /
  ``quantile`` / ``avg_over`` queries and JSONL export/replay;
* :mod:`~distributedkernelshap_tpu.observability.slo` — declarative SLOs
  (availability, latency-threshold, staleness) evaluated as multi-window
  multi-burn-rate conditions over the store, with per-priority-class
  latency targets;
* :mod:`~distributedkernelshap_tpu.observability.alerts` — the alert
  rules engine (pending → firing → resolved, for/keep-firing durations,
  dedup, silences) with pluggable sinks (log, flight recorder, webhook,
  ``dks_alerts_firing`` gauge);
* :mod:`~distributedkernelshap_tpu.observability.statusz` — the
  :class:`HealthEngine` bundling sampler + SLOs + alerts behind the
  ``/statusz`` endpoint both serving components expose;
* :mod:`~distributedkernelshap_tpu.observability.contprof` — the
  always-on sampling wall-clock profiler (``sys._current_frames`` at a
  prime default rate) behind ``/profilez``, with role/tenant-tagged
  collapsed stacks, Perfetto export and federated merging;
* :mod:`~distributedkernelshap_tpu.observability.memledger` — the
  process-wide device-memory ledger: per-owner/per-tenant computed
  byte accounting over every device-resident cache, with a soft budget
  and pressure-driven LRU eviction;
* :mod:`~distributedkernelshap_tpu.observability.quality` — continuous
  correctness: the in-band invariant auditor (additivity/NaN/error-bound
  screen on every served answer, ``/qualityz`` repro ring), the budgeted
  shadow-oracle sampler (billed to the ``_quality`` tenant under
  ``DKS_QUALITY_BUDGET_S``) and the hot-swap canary drift sentinel.
  Stdlib-only at module scope like its siblings — numpy and the wire
  codec load lazily inside the screening calls.

See ``docs/OBSERVABILITY.md`` for the metric catalog, trace header
format, SLO/alert semantics, ``/statusz`` schema, ``/debugz`` schema and
Perfetto how-to.
"""

# NOTE: the ``flightrec()`` accessor function is deliberately NOT
# re-exported here — it shares its name with its submodule, and binding it
# on the package would shadow ``observability.flightrec`` for module-path
# imports.  Import it from the submodule:
# ``from distributedkernelshap_tpu.observability.flightrec import flightrec``.
from distributedkernelshap_tpu.observability.alerts import (  # noqa: F401
    AlertManager,
    AlertRule,
    CollectSink,
    FlightRecorderSink,
    LogSink,
    Silence,
    WebhookSink,
    slo_burn_rule,
)
from distributedkernelshap_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder,
)
from distributedkernelshap_tpu.observability.costmeter import (  # noqa: F401
    CostMeter,
)
from distributedkernelshap_tpu.observability.contprof import (  # noqa: F401
    ContProf,
    merge_collapsed,
    parse_collapsed,
)
from distributedkernelshap_tpu.observability.memledger import (  # noqa: F401
    MemLedger,
    TrackedCache,
    approx_nbytes,
)
from distributedkernelshap_tpu.observability.fleet import (  # noqa: F401
    fleet_rollup,
    merge_expositions,
)
from distributedkernelshap_tpu.observability.slo import (  # noqa: F401
    AvailabilitySLO,
    BurnRateWindow,
    LatencySLO,
    QualitySLO,
    SLO,
    StalenessSLO,
    default_proxy_slos,
    default_server_slos,
    tenant_slos,
)
from distributedkernelshap_tpu.observability.quality import (  # noqa: F401
    QualityAuditor,
    QualityMonitor,
    ShadowSampler,
    CanarySentinel,
    merge_quality_pages,
    screen_arrays,
    screen_payload,
)
from distributedkernelshap_tpu.observability.statusz import (  # noqa: F401
    HealthEngine,
    render_statusz_html,
    statusz_response,
)
from distributedkernelshap_tpu.observability.timeseries import (  # noqa: F401
    RegistrySampler,
    TimeSeriesStore,
    load_jsonl,
    sparkline,
)
from distributedkernelshap_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    parse_exposition,
    validate_exposition,
)
from distributedkernelshap_tpu.observability.tracing import (  # noqa: F401
    TRACE_HEADER,
    Span,
    SpanContext,
    Tracer,
    current_context,
    format_trace_header,
    parse_trace_header,
    tracer,
    use_context,
)
