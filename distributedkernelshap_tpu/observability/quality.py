"""Continuous correctness observability: is the phi we serve *right*?

Eighteen PRs of observability watch latency, resources and cost — none
of them watch the statistical contract the whole system exists to
honour.  KernelSHAP's constrained WLS enforces the efficiency axiom, so
every healthy answer satisfies **additivity**:
``sum_m(phi[k][b, m]) + E[f]_k ≈ f(x_b)_k`` (link space) to solver
precision — a live invariant cheap enough to check on every answer.
This module turns it (plus NaN/Inf screening and anytime error-bound
sanity) into an alertable production signal, in three tiers:

1. :class:`QualityAuditor` — **in-band invariant auditor**.  Every
   served explanation is screened host-side at finalize time (pure
   payload parsing, no device work).  Violations count in
   ``dks_quality_violations_total{model,path,check}``, land on the
   flight recorder as ``quality_violation`` events with trace
   exemplars, and the offending request is captured into a bounded
   repro ring served on ``/qualityz``.
2. :class:`ShadowSampler` — **budgeted shadow-oracle sampler**.  A
   background thread re-explains a sampled fraction of recent live
   traffic at higher fidelity: tenants on an exact path
   (exact/exact_tn/deepshap — in-fleet ground-truth oracles) are
   re-run as their own oracle; sampled-path tenants get a
   high-``nsamples`` re-run.  Per-tenant served-vs-oracle error is
   tracked as a bounded time-series and exposed as
   ``dks_quality_shadow_err{model}``.  Oracle device-seconds are
   charged to the ``_quality`` system tenant through the cost meter
   and capped by a hard ``DKS_QUALITY_BUDGET_S`` budget — auditing is
   a metered tenant, not an unmetered tax.
3. :class:`CanarySentinel` — **hot-swap/canary drift sentinel**.  Each
   registration auto-captures a small golden canary set (background
   rows + their phi).  The registry replays it against every incoming
   version *before traffic moves* (the ``model_swap`` flight event
   carries the quantified drift verdict) and the monitor thread
   replays it periodically against the live fleet.

One :class:`QualityMonitor` composes the three per server (like
``CostMeter`` — per-registry, not process-global).  Env knobs:
``DKS_QUALITY_AUDIT`` (default on), ``DKS_QUALITY_SAMPLE`` (shadow
fraction, default 0 = off), ``DKS_QUALITY_BUDGET_S`` (shadow budget,
default 30).  Stdlib-only at module scope like the rest of
``observability/``; numpy and the wire codec are imported lazily inside
the screening calls.
"""

import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.costmeter import OVERFLOW_LABEL
from distributedkernelshap_tpu.observability.flightrec import flightrec

logger = logging.getLogger(__name__)

#: the system tenant the shadow oracle's device-seconds bill to
QUALITY_TENANT = "_quality"

#: the invariant screen's check names (the ``check`` label values)
CHECKS = ("additivity", "finite", "error_bound", "decode")

#: engine paths whose served answer is already ground truth — the shadow
#: oracle re-runs them as their own oracle (drift there means
#: nondeterminism or device fault, not estimator variance).  ``linear``
#: is NOT here: the registry's linear path is still the sampled
#: estimator (only its plan is cached), so its oracle is a
#: high-nsamples re-run like ``sampled``.
EXACT_PATHS = ("exact", "exact_tree", "exact_tn", "deepshap")

#: per-path additivity tolerance ``(rtol, atol)`` on
#: ``|sum(phi) + E[f] - f(x)|``: exact paths solve in closed form (f32
#: accumulation noise only); DeepSHAP distributes exactly but through a
#: longer backprop chain; the sampled WLS enforces the efficiency
#: constraint to regularized-solver precision, the loosest of the three.
#: Keys cover BOTH path vocabularies that reach the auditor: the
#: wrapper's explain path (``exact``/``deepshap``/``sampled``,
#: ``wrappers._resolve_explain_path``) and the registry's engine path
#: (``linear``/``exact_tree``/``exact_tn``/``deepshap``/``sampled``,
#: ``registry/classify.ENGINE_PATHS``) — ``linear`` and ``exact_tree``
#: dispatch exact or plan-cached solves and screen at the tight bound.
PATH_TOLERANCES = {
    "exact": (1e-3, 1e-4),
    "exact_tree": (1e-3, 1e-4),
    "exact_tn": (1e-3, 1e-4),
    "linear": (1e-3, 1e-4),
    "deepshap": (5e-3, 1e-4),
    "sampled": (1e-2, 1e-3),
}
DEFAULT_TOLERANCE = (1e-2, 1e-3)

#: reported anytime error bounds above this are nonsense, not progress
MAX_SANE_ERR = 1e3

#: canary drift at/below this is recompile noise; above it is a verdict
DRIFT_TOLERANCE = 1e-3

DEFAULT_RING = 32            #: repro-ring capacity (offending requests)
DEFAULT_QUEUE = 64           #: shadow sample queue capacity
DEFAULT_AUDIT_QUEUE = 1024   #: deferred-audit queue capacity (drop-oldest)
DEFAULT_SERIES = 120         #: per-tenant shadow error time-series points
DEFAULT_BUDGET_S = 30.0      #: DKS_QUALITY_BUDGET_S default
DEFAULT_ORACLE_NSAMPLES = 2048
DEFAULT_CANARY_ROWS = 4
DEFAULT_CANARY_INTERVAL_S = 60.0
DEFAULT_MAX_TENANTS = 64     #: label cap, mirrors the cost meter's


def resolve_audit_env(default: bool = True) -> bool:
    """``DKS_QUALITY_AUDIT``: the in-band invariant auditor (default on)."""

    from distributedkernelshap_tpu.utils import resolve_bool_env

    return resolve_bool_env("DKS_QUALITY_AUDIT", default)


def resolve_sample_env(default: float = 0.0) -> float:
    """``DKS_QUALITY_SAMPLE``: shadow-oracle sampling fraction in [0, 1]
    (default 0 — the sampler is off unless opted in)."""

    raw = os.environ.get("DKS_QUALITY_SAMPLE", "").strip()
    if not raw:
        return default
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        logger.warning("DKS_QUALITY_SAMPLE=%r is not a float; using %s",
                       raw, default)
        return default


def resolve_budget_env(default: float = DEFAULT_BUDGET_S) -> float:
    """``DKS_QUALITY_BUDGET_S``: hard cap on shadow-oracle device-seconds
    per process lifetime."""

    raw = os.environ.get("DKS_QUALITY_BUDGET_S", "").strip()
    if not raw:
        return default
    try:
        return max(0.0, float(raw))
    except ValueError:
        logger.warning("DKS_QUALITY_BUDGET_S=%r is not a float; using %s",
                       raw, default)
        return default


# --------------------------------------------------------------------- #
# invariant screen
# --------------------------------------------------------------------- #

def payload_arrays(payload) -> Dict:
    """Transport-agnostic decode of one served explanation payload —
    JSON ``Explanation`` string or binary DKSW bytes — to
    ``{'shap_values': [K x (B, M)], 'expected_value': (K,),
    'raw_prediction': (B, K)}``."""

    from distributedkernelshap_tpu.serving import wire

    if isinstance(payload, (bytes, bytearray)):
        if payload[:4] == b"DKSW":  # the binary wire magic
            return wire.decode_explanation(bytes(payload))
        payload = bytes(payload).decode("utf-8")  # JSON shipped as bytes
    return wire.explanation_payload_from_json(payload)


def screen_arrays(shap_values, expected_value, raw_prediction,
                  path: str = "sampled",
                  final_err: float = 0.0) -> List[Tuple[str, str]]:
    """Screen one answer's arrays against the serving invariants.
    Returns ``[(check, detail), ...]`` — empty means clean.

    Checks: ``finite`` (NaN/Inf anywhere — a non-finite element in phi,
    ``E[f]`` or ``f(x)`` propagates into the row-sum residual, so ONE
    finiteness test on the residual screens all three arrays; this is
    what keeps the screen cheap enough to ride every finalize),
    ``error_bound`` (a reported anytime bound must be a sane
    non-negative float), ``additivity`` (``|sum(phi) + E[f] - f(x)| <=
    atol + final_err + rtol * max(1, |f(x)|)`` per row and output,
    path-specific tolerance — an anytime answer served under a declared
    error budget widens the bound by exactly that budget)."""

    import numpy as np

    violations: List[Tuple[str, str]] = []
    sv = shap_values if isinstance(shap_values, list) else [shap_values]
    ev = np.asarray(expected_value, dtype=np.float64)
    if ev.ndim != 1:
        ev = ev.reshape(-1)
    raw = np.asarray(raw_prediction, dtype=np.float64)
    if raw.ndim != 2:
        raw = raw.reshape(1, -1) if raw.ndim <= 1 \
            else raw.reshape(raw.shape[0], -1)
    fe = float(final_err or 0.0)
    if fe != fe or not (0.0 <= fe <= MAX_SANE_ERR):
        violations.append((
            "error_bound",
            f"reported error bound {final_err!r} outside "
            f"[0, {MAX_SANE_ERR:g}]"))
        fe = 0.0
    k = min(len(sv), ev.shape[0], raw.shape[-1])
    if k <= 0:
        return violations
    sums = [np.asarray(sv[i], dtype=np.float64).sum(axis=-1).reshape(-1)
            for i in range(k)]
    resid = np.stack(sums, axis=-1) + ev[:k] - raw[..., :k]
    if not np.isfinite(resid).all():
        violations.insert(0, ("finite",
                              "NaN/Inf in phi/expected_value/"
                              "raw_prediction"))
        return violations  # additivity over non-finite values is noise
    resid = np.abs(resid)
    rtol, atol = PATH_TOLERANCES.get(path, DEFAULT_TOLERANCE)
    bound = atol + fe + rtol * np.maximum(1.0, np.abs(raw[..., :k]))
    if bool((resid > bound).any()):
        violations.append((
            "additivity",
            f"max |sum(phi)+E[f]-f(x)| = {float(resid.max()):.3g} "
            f"(bound {float(bound.max()):.3g}, path={path})"))
    return violations


def screen_payload(payload, path: str = "sampled", final_err: float = 0.0
                   ) -> Tuple[List[Tuple[str, str]], Optional[Dict]]:
    """Decode + screen one payload; ``(violations, arrays-or-None)``.
    A payload that will not even decode is itself a violation
    (``decode``) — it could never be replayed or cached safely."""

    try:
        arrays = payload_arrays(payload)
    except Exception as exc:  # noqa: BLE001 — any decode failure is the signal
        return [("decode", f"payload failed to decode: {exc}")], None
    return screen_arrays(arrays["shap_values"], arrays["expected_value"],
                         arrays["raw_prediction"], path=path,
                         final_err=final_err), arrays


def cacheable_payload(payload, path: str = "sampled",
                      final_err: float = 0.0) -> bool:
    """Audit-on-insert hook for the keep-best result cache: may this
    payload be cached?  A phi payload failing the invariant screen must
    never become a bit-identical repeat offender.  Payloads that do not
    decode as explanations at all pass through — the cache is generic
    keyed storage and its historical contract accepts arbitrary strings;
    only *decodable-but-wrong phi* is poison worth blocking here (the
    server's in-band auditor separately catches undecodable answers
    before its own put).  Honours ``DKS_QUALITY_AUDIT`` (screen off ⇒
    everything passes, the pre-quality behaviour)."""

    if not resolve_audit_env(True):
        return True
    try:
        arrays = payload_arrays(payload)
    except Exception:  # noqa: BLE001 — not an explanation document
        return True
    return not screen_arrays(arrays["shap_values"],
                             arrays["expected_value"],
                             arrays["raw_prediction"], path=path,
                             final_err=final_err)


# --------------------------------------------------------------------- #
# tier 1: in-band invariant auditor
# --------------------------------------------------------------------- #

class QualityAuditor:
    """Screens every served answer at finalize time; keeps a bounded
    repro ring of offenders for ``/qualityz``.  Pure host-side payload
    parsing — never touches the device, so it rides the finalizer
    threads within the ≤1 % overhead budget the bench enforces."""

    def __init__(self, enabled: bool = True, ring_size: int = DEFAULT_RING):
        self.enabled = bool(enabled)
        self.ring_size = int(ring_size)
        self._lock = lockwitness.make_lock("quality.auditor")
        self._ring: deque = deque(maxlen=self.ring_size)
        self._audited = 0
        self._violation_answers = 0
        self._flight = flightrec()
        # bound metric objects + label guard, injected by the monitor
        self._on_violation = None

    def audit(self, payload, model_id: Optional[str] = None,
              path: str = "sampled", final_err: float = 0.0,
              trace: Optional[str] = None
              ) -> Tuple[bool, Optional[Dict]]:
        """Screen one served payload.  Returns ``(ok, arrays-or-None)``
        — the parsed arrays are handed back so the shadow sampler never
        pays a second decode."""

        if not self.enabled:
            return True, None
        violations, arrays = screen_payload(payload, path=path,
                                            final_err=final_err)
        with self._lock:
            self._audited += 1
        if not violations:
            return True, arrays
        checks = [c for c, _ in violations]
        detail = "; ".join(d for _, d in violations)
        if isinstance(payload, (bytes, bytearray)):
            prefix = payload[:160].hex()
        else:
            prefix = str(payload)[:160]
        entry = {
            "ts": time.time(),
            "model": model_id or "default",
            "path": path,
            "checks": checks,
            "detail": detail,
            "final_err": float(final_err or 0.0),
            "trace": trace,
            "payload_prefix": prefix,
        }
        with self._lock:
            self._ring.append(entry)
            self._violation_answers += 1
        self._flight.record("quality_violation", model=model_id or "default",
                            path=path, checks=checks, detail=detail,
                            trace=trace)
        if self._on_violation is not None:
            for check in checks:
                self._on_violation(model_id, path, check)
        return False, arrays

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "audited_total": self._audited,
                "violation_answers_total": self._violation_answers,
                "ring_size": self.ring_size,
                "ring": list(self._ring),
            }


# --------------------------------------------------------------------- #
# tier 2: budgeted shadow-oracle sampler
# --------------------------------------------------------------------- #

class ShadowSampler:
    """Re-explains a sampled fraction of live answers at oracle
    fidelity on a background thread, under a hard device-seconds
    budget.  ``offer()`` is called from the serving finalizer (cheap:
    one RNG draw + a bounded deque append); ``drain_once()`` runs the
    oracle off the hot path."""

    def __init__(self, fraction: float = 0.0,
                 budget_s: float = DEFAULT_BUDGET_S,
                 costmeter=None,
                 oracle_nsamples: int = DEFAULT_ORACLE_NSAMPLES,
                 queue_size: int = DEFAULT_QUEUE,
                 series_size: int = DEFAULT_SERIES,
                 seed: int = 0):
        self.fraction = float(fraction)
        self.budget_s = float(budget_s)
        self.oracle_nsamples = int(oracle_nsamples)
        self.queue_size = int(queue_size)
        self.series_size = int(series_size)
        self._costmeter = costmeter
        self._lock = lockwitness.make_lock("quality.shadow")
        self._rng = random.Random(seed)
        self._queue: deque = deque()
        self._spent_s = 0.0          # wall-measured oracle seconds
        self._last_run_s = 0.0       # EWMA of one oracle run's cost
        self._max_run_s = 0.0        # costliest run seen (budget guard)
        self._exhausted = False
        self._offered = 0
        self._sampled = 0
        self._dropped = 0
        self._runs: Dict[str, int] = {}
        self._err: Dict[str, float] = {}
        self._series: Dict[str, deque] = {}

    # -- hot-path side -------------------------------------------------- #

    def offer(self, model_id: Optional[str], path: str, model,
              rows, served_sv) -> bool:
        """Maybe enqueue one live answer for shadow re-explanation.
        ``served_sv`` is the already-parsed phi list (the auditor's
        decode is reused — no second parse on the hot path)."""

        if self.fraction <= 0.0 or model is None or rows is None \
                or served_sv is None:
            return False
        with self._lock:
            self._offered += 1
            if self._exhausted or self._rng.random() >= self.fraction:
                return False
            if len(self._queue) >= self.queue_size:
                self._dropped += 1
                return False
            self._queue.append((model_id or "default", path, model,
                                rows, served_sv))
            self._sampled += 1
        return True

    # -- background side ------------------------------------------------ #

    def _budget_allows(self) -> bool:
        """A run may start only if the budget projects clean: spent plus
        the costliest run seen must stay under the hard cap.  A run
        cannot be preempted mid-explain, so the cap's contract is
        pre-gated: overspend is bounded by how much one run exceeds its
        projection (at most one run's cost in total).  The very first
        run has no estimate and is allowed — the operator contract is
        that the budget exceeds a single oracle run."""

        with self._lock:
            if self._exhausted:
                return False
            if self._spent_s + self._max_run_s >= self.budget_s:
                self._exhausted = True
                logger.warning(
                    "shadow-oracle budget exhausted: %.3fs spent of "
                    "%.3fs (DKS_QUALITY_BUDGET_S)", self._spent_s,
                    self.budget_s)
                return False
        return True

    def _oracle_kwargs(self, path: str, model) -> Dict:
        kwargs = {k: v for k, v in
                  dict(getattr(model, "explain_kwargs", None) or {}).items()
                  if k in ("nsamples", "l1_reg")}
        if path not in EXACT_PATHS:
            base = kwargs.get("nsamples")
            base = base if isinstance(base, int) else 0
            kwargs["nsamples"] = max(base, self.oracle_nsamples)
        return kwargs

    def drain_once(self) -> Optional[Dict]:
        """Run the oracle for at most one queued sample.  Returns
        ``{'model', 'path', 'err', 'rows', 'seconds'}`` when a run
        happened, else ``None``.  Device time is wall-bracketed for the
        budget AND settled to the cost meter under the ``_quality``
        system tenant (compile time excluded, the meter's rule)."""

        import numpy as np

        with self._lock:
            item = self._queue.popleft() if self._queue else None
        if item is None or not self._budget_allows():
            return None
        model_id, path, model, rows, served_sv = item
        rows = np.atleast_2d(np.asarray(rows))
        kwargs = self._oracle_kwargs(path, model)
        meter = self._costmeter
        tx = meter.begin() if meter is not None else None
        t0 = time.monotonic()
        try:
            explanation = model.explainer.explain(rows, silent=True,
                                                  **kwargs)
        except Exception:
            logger.exception("shadow-oracle re-explain failed for %s",
                             model_id)
            if meter is not None:
                meter.settle(tx, [(QUALITY_TENANT, 0, path,
                                   int(rows.shape[0]))])
            return None
        elapsed = time.monotonic() - t0
        if meter is not None:
            # the meter subtracts compile seconds; elapsed (wall) is the
            # conservative number the budget accrues
            meter.settle(tx, [(QUALITY_TENANT, 0, path,
                               int(rows.shape[0]))])
        oracle_sv = explanation.shap_values
        oracle_sv = oracle_sv if isinstance(oracle_sv, list) else [oracle_sv]
        k = min(len(oracle_sv), len(served_sv))
        err = 0.0
        for i in range(k):
            a = np.atleast_2d(np.asarray(served_sv[i], dtype=np.float64))
            b = np.atleast_2d(np.asarray(oracle_sv[i], dtype=np.float64))
            n = min(a.shape[0], b.shape[0])
            m = min(a.shape[1], b.shape[1])
            if n and m:
                err = max(err, float(np.abs(a[:n, :m] - b[:n, :m]).max()))
        now = time.time()
        with self._lock:
            self._spent_s += elapsed
            self._last_run_s = elapsed if self._last_run_s == 0.0 \
                else 0.5 * self._last_run_s + 0.5 * elapsed
            self._max_run_s = max(self._max_run_s, elapsed)
            self._runs[model_id] = self._runs.get(model_id, 0) + 1
            self._err[model_id] = err
            series = self._series.setdefault(
                model_id, deque(maxlen=self.series_size))
            series.append((now, err))
        return {"model": model_id, "path": path, "err": err,
                "rows": int(rows.shape[0]), "seconds": elapsed}

    def spent_seconds(self) -> float:
        with self._lock:
            return self._spent_s

    def retire(self, model_id: str) -> None:
        with self._lock:
            self._runs.pop(model_id, None)
            self._err.pop(model_id, None)
            self._series.pop(model_id, None)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "fraction": self.fraction,
                "budget_s": self.budget_s,
                "spent_s": self._spent_s,
                "max_run_s": self._max_run_s,
                "exhausted": self._exhausted,
                "offered": self._offered,
                "sampled": self._sampled,
                "dropped": self._dropped,
                "queued": len(self._queue),
                "tenants": {
                    mid: {"runs": self._runs.get(mid, 0),
                          "last_err": self._err.get(mid),
                          "series": [[t, e] for t, e in
                                     self._series.get(mid, ())]}
                    for mid in sorted(set(self._runs) | set(self._err))},
            }


# --------------------------------------------------------------------- #
# tier 3: hot-swap/canary drift sentinel
# --------------------------------------------------------------------- #

class CanarySentinel:
    """Golden canary set per tenant: a few background rows plus their
    phi, captured at registration.  ``swap_check`` replays the stored
    baseline against an incoming version *before the registry flips
    traffic*; the monitor thread replays periodically against the live
    model (catching silent drift between swaps — dead device handles,
    recompile changes, background mutation)."""

    def __init__(self, n_rows: int = DEFAULT_CANARY_ROWS):
        self.n_rows = int(n_rows)
        self._lock = lockwitness.make_lock("quality.canary")
        self._baselines: Dict[str, Dict] = {}
        self._drift: Dict[str, Dict] = {}
        self._flight = flightrec()

    def canary_rows(self, model):
        """Deterministic canary inputs for one model: the first few
        background rows (always in-distribution, always present on a
        fitted explainer).  ``None`` for models without an inspectable
        engine (stubs) — the sentinel then stays inert for them."""

        import numpy as np

        engine = getattr(getattr(model, "explainer", None), "_explainer",
                         None)
        background = getattr(engine, "background", None)
        if background is None:
            return None
        background = np.asarray(background)
        if background.ndim != 2 or not background.shape[0]:
            return None
        return np.array(background[:min(self.n_rows, background.shape[0])])

    def _phi(self, model, rows) -> List:
        kwargs = {k: v for k, v in
                  dict(getattr(model, "explain_kwargs", None) or {}).items()
                  if k in ("nsamples", "l1_reg")}
        explanation = model.explainer.explain(rows, silent=True, **kwargs)
        sv = explanation.shap_values
        return sv if isinstance(sv, list) else [sv]

    def capture(self, model_id: str, model,
                fingerprint: Optional[str] = None) -> bool:
        """(Re-)capture the golden baseline for one tenant from the
        version about to serve.  Returns whether a baseline exists."""

        rows = self.canary_rows(model)
        if rows is None:
            return False
        phi = self._phi(model, rows)
        with self._lock:
            self._baselines[model_id] = {
                "rows": rows, "phi": phi,
                "fingerprint": fingerprint, "ts": time.time()}
        return True

    def _max_drift(self, baseline_phi, phi) -> float:
        import numpy as np

        drift = 0.0
        for i in range(min(len(baseline_phi), len(phi))):
            a = np.atleast_2d(np.asarray(baseline_phi[i], dtype=np.float64))
            b = np.atleast_2d(np.asarray(phi[i], dtype=np.float64))
            n, m = min(a.shape[0], b.shape[0]), min(a.shape[1], b.shape[1])
            if n and m:
                drift = max(drift,
                            float(np.abs(a[:n, :m] - b[:n, :m]).max()))
        return drift

    def replay(self, model_id: str, model,
               record_event: bool = True) -> Optional[Dict]:
        """Replay the stored baseline's rows through ``model`` and
        quantify phi drift.  ``None`` when no baseline exists (first
        registration, stub model).  A drift verdict lands on the
        flight recorder as a ``swap_drift`` event."""

        with self._lock:
            base = self._baselines.get(model_id)
        if base is None:
            return None
        try:
            phi = self._phi(model, base["rows"])
        except Exception:
            logger.exception("canary replay failed for %s", model_id)
            return None
        drift = self._max_drift(base["phi"], phi)
        verdict = "ok" if drift <= DRIFT_TOLERANCE else "drift"
        result = {"model": model_id, "drift": drift, "verdict": verdict,
                  "rows": int(base["rows"].shape[0]), "ts": time.time()}
        with self._lock:
            self._drift[model_id] = result
        if record_event and verdict == "drift":
            self._flight.record("swap_drift", model=model_id, drift=drift,
                                rows=result["rows"],
                                threshold=DRIFT_TOLERANCE)
        return result

    def swap_check(self, model_id: str, model,
                   fingerprint: Optional[str] = None) -> Optional[Dict]:
        """Registry hook for one version flip: replay the OLD baseline
        against the NEW version (the drift verdict the ``model_swap``
        event carries), then re-capture the baseline from the version
        about to serve.  ``None`` on first registration."""

        verdict = self.replay(model_id, model)
        self.capture(model_id, model, fingerprint=fingerprint)
        return verdict

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._baselines)

    def retire(self, model_id: str) -> None:
        with self._lock:
            self._baselines.pop(model_id, None)
            self._drift.pop(model_id, None)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "threshold": DRIFT_TOLERANCE,
                "tenants": {
                    mid: {
                        "rows": int(base["rows"].shape[0]),
                        "fingerprint": base.get("fingerprint"),
                        "captured_ts": base.get("ts"),
                        "drift": self._drift.get(mid, {}).get("drift"),
                        "verdict": self._drift.get(mid, {}).get("verdict"),
                    } for mid, base in self._baselines.items()},
            }


# --------------------------------------------------------------------- #
# composition root
# --------------------------------------------------------------------- #

class QualityMonitor:
    """One per :class:`ExplainerServer` (the obs-check live catalog
    builds several servers in one process — per-registry, never a
    process singleton).  Owns the metric bindings, the ``/qualityz``
    document, the background drain/canary thread and the bounded tenant
    label guard."""

    def __init__(self, server=None, costmeter=None,
                 audit: Optional[bool] = None,
                 sample: Optional[float] = None,
                 budget_s: Optional[float] = None,
                 ring_size: int = DEFAULT_RING,
                 canary_interval_s: float = DEFAULT_CANARY_INTERVAL_S,
                 max_tenants: int = DEFAULT_MAX_TENANTS):
        self._server = server
        self.canary_interval_s = float(canary_interval_s)
        self.max_tenants = int(max_tenants)
        self.auditor = QualityAuditor(
            enabled=resolve_audit_env(True) if audit is None else audit,
            ring_size=ring_size)
        self.sampler = ShadowSampler(
            fraction=resolve_sample_env(0.0) if sample is None else sample,
            budget_s=resolve_budget_env() if budget_s is None else budget_s,
            costmeter=costmeter)
        self.sentinel = CanarySentinel()
        self.auditor._on_violation = self._count_violation
        self._label_lock = lockwitness.make_lock("quality.labels")
        self._labels: set = set()
        # deferred-audit queue: the serving finalizer enqueues (cheap —
        # one append + an event) and the monitor thread runs the actual
        # decode+screen, so the audit's cost never rides the GIL while a
        # waiter is trying to write the response out
        self._audit_lock = lockwitness.make_lock("quality.audit_queue")
        self._audit_queue: deque = deque()
        self._audit_dropped = 0
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_violations = None
        self._m_shadow_err = None
        self._m_shadow_runs = None
        self._m_canary = None

    # -- bounded tenant labels ------------------------------------------ #

    def label(self, model_id: Optional[str]) -> str:
        mid = "default" if not model_id else str(model_id)
        with self._label_lock:
            if mid in self._labels or len(self._labels) < self.max_tenants:
                self._labels.add(mid)
                return mid
        return OVERFLOW_LABEL

    # -- metrics -------------------------------------------------------- #

    def attach_metrics(self, registry) -> None:
        registry.counter(
            "dks_quality_audited_total",
            "Served answers screened by the in-band invariant auditor "
            "(additivity + NaN/Inf + anytime error-bound sanity, "
            "host-side at finalize time).").set_function(
            lambda: float(self.auditor.snapshot()["audited_total"]))
        self._m_violations = registry.counter(
            "dks_quality_violations_total",
            "Invariant-screen violations on served answers, by tenant, "
            "engine path and failed check (additivity | finite | "
            "error_bound | decode).  Offenders land on the flight "
            "recorder and the /qualityz repro ring.",
            labelnames=("model", "path", "check")).bound_cardinality(
            self.max_tenants * len(CHECKS) * 8)
        # the metric handles below are assigned once here, before start()
        # spawns the monitor thread; the thread only reads the references
        # dks: allow(DKS-C001): set-once-before-start handle
        self._m_shadow_err = registry.gauge(
            "dks_quality_shadow_err",
            "Last served-vs-oracle max-abs phi error per tenant from the "
            "budgeted shadow-oracle sampler (exact paths re-run as their "
            "own oracle; sampled paths re-run at high nsamples).",
            labelnames=("model",)).bound_cardinality(self.max_tenants)
        # dks: allow(DKS-C001): set-once-before-start handle
        self._m_shadow_runs = registry.counter(
            "dks_quality_shadow_runs_total",
            "Completed shadow-oracle re-explanations per tenant.",
            labelnames=("model",)).bound_cardinality(self.max_tenants)
        registry.counter(
            "dks_quality_shadow_seconds_total",
            "Wall seconds the shadow oracle has consumed — accrues "
            "toward the hard DKS_QUALITY_BUDGET_S cap; the same work is "
            "billed to the _quality tenant in dks_device_seconds_total."
        ).set_function(self.sampler.spent_seconds)
        # dks: allow(DKS-C001): set-once-before-start handle
        self._m_canary = registry.gauge(
            "dks_quality_canary_drift",
            "Max-abs phi drift of the latest canary replay per tenant "
            "(version flips replay before traffic moves; the monitor "
            "thread replays periodically).",
            labelnames=("model",)).bound_cardinality(self.max_tenants)

    def _count_violation(self, model_id: Optional[str], path: str,
                         check: str) -> None:
        if self._m_violations is not None:
            self._m_violations.inc(model=self.label(model_id),
                                   path=str(path), check=str(check))

    # -- hot-path entry point ------------------------------------------- #

    def inspect_answer(self, payload, model_id: Optional[str] = None,
                       path: str = "sampled", final_err: float = 0.0,
                       rows=None, model=None,
                       trace: Optional[str] = None) -> bool:
        """Tier-1 screen for one served answer (called from the server's
        ``_complete``); feeds the tier-2 sampler with the parsed arrays.
        Returns whether the answer passed (a failing answer must not be
        cached)."""

        if not self.auditor.enabled and self.sampler.fraction <= 0.0:
            return True
        ok, arrays = True, None
        if self.auditor.enabled:
            ok, arrays = self.auditor.audit(payload, model_id=model_id,
                                            path=path, final_err=final_err,
                                            trace=trace)
        if ok and self.sampler.fraction > 0.0 and arrays is None:
            # auditor off: the sampler pays its own decode
            try:
                arrays = payload_arrays(payload)
            except Exception:
                arrays = None
        if ok and arrays is not None:
            self.sampler.offer(model_id, path, model, rows,
                               arrays.get("shap_values"))
        return ok

    def enqueue_answer(self, payload, model_id: Optional[str] = None,
                       path: str = "sampled", final_err: float = 0.0,
                       rows=None, model=None, trace: Optional[str] = None,
                       cache=None, cache_key: Optional[str] = None) -> None:
        """Queue one served answer for the deferred invariant screen —
        the serving hot path's entry point (one bounded append; the
        screen itself runs on the monitor thread).  The queue is drained
        in batches on the monitor tick rather than per-enqueue: an
        immediate wake would contend for the GIL with the handler thread
        still writing the response out, putting the screen's cost right
        back on the latency path it was moved off of.  Detection latency
        is therefore bounded by the drain tick, not by traffic.  A
        cached answer that later fails the screen is invalidated out of
        ``cache`` (the insert stays on the finalizer; poison lives at
        most one drain cycle)."""

        if not self.auditor.enabled and self.sampler.fraction <= 0.0:
            return
        with self._audit_lock:
            if len(self._audit_queue) >= DEFAULT_AUDIT_QUEUE:
                self._audit_queue.popleft()  # drop-oldest under overload
                self._audit_dropped += 1
            self._audit_queue.append((payload, model_id, path, final_err,
                                      rows, model, trace, cache, cache_key))

    def _drain_audits(self) -> None:
        while True:
            with self._audit_lock:
                item = self._audit_queue.popleft() if self._audit_queue \
                    else None
            if item is None:
                return
            (payload, model_id, path, final_err, rows, model, trace,
             cache, cache_key) = item
            ok = self.inspect_answer(payload, model_id=model_id, path=path,
                                     final_err=final_err, rows=rows,
                                     model=model, trace=trace)
            if not ok and cache is not None and cache_key is not None:
                cache.invalidate(cache_key, audit=True)

    def audit_backlog(self) -> int:
        with self._audit_lock:
            return len(self._audit_queue)

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Wait until the deferred-audit queue is empty (tests/benches
        that need the screen's verdict for everything already served).
        Drains inline when no monitor thread is running."""

        deadline = time.monotonic() + timeout_s
        while True:
            with self._audit_lock:
                empty = not self._audit_queue
            if empty:
                return True
            if self._thread is None:
                self._drain_audits()
                continue
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    # -- swap / retirement hooks ---------------------------------------- #

    def swap_check(self, model_id: str, model,
                   fingerprint: Optional[str] = None) -> Optional[Dict]:
        verdict = self.sentinel.swap_check(model_id, model,
                                           fingerprint=fingerprint)
        if verdict is not None and self._m_canary is not None:
            self._m_canary.set(verdict["drift"], model=self.label(model_id))
        return verdict

    def retire_tenant(self, model_id: str, registry=None) -> None:
        """Drop one tenant's quality state and metric series (registry
        unregister path — label churn must not grow the registry)."""

        self.sampler.retire(model_id)
        self.sentinel.retire(model_id)
        with self._label_lock:
            self._labels.discard(str(model_id))
        if registry is not None:
            for name in ("dks_quality_violations_total",
                         "dks_quality_shadow_err",
                         "dks_quality_shadow_runs_total",
                         "dks_quality_canary_drift"):
                registry.retire_labels(name, {"model": str(model_id)})

    # -- background thread ---------------------------------------------- #

    def start(self, tick_s: float = 0.25) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, args=(tick_s,),
                                        daemon=True, name="dks-quality")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
        self._drain_audits()  # bounded: leave no unscreened backlog behind

    def _active_models(self) -> List[Tuple[str, object]]:
        server = self._server
        if server is None:
            return []
        registry = getattr(server, "_registry", None)
        if registry is not None:
            try:
                return [(rm.model_id, rm.model)
                        for rm in registry.active_models()]
            except Exception:  # noqa: BLE001 — roster race, skip this sweep
                return []
        model = getattr(server, "model", None)
        return [("default", model)] if model is not None else []

    def _loop(self, tick_s: float) -> None:
        next_canary = time.monotonic() + self.canary_interval_s
        while not self._stop.is_set():
            self._work.wait(tick_s)  # enqueues wake the drain immediately
            self._work.clear()
            if self._stop.is_set():
                return
            try:  # guarded per-iteration: one bad sweep must not kill probing
                self._drain_audits()
                result = self.sampler.drain_once()
                if result is not None:
                    mid = self.label(result["model"])
                    if self._m_shadow_err is not None:
                        self._m_shadow_err.set(result["err"], model=mid)
                    if self._m_shadow_runs is not None:
                        self._m_shadow_runs.inc(model=mid)
                if self.canary_interval_s > 0 \
                        and time.monotonic() >= next_canary:
                    next_canary = time.monotonic() + self.canary_interval_s
                    self._periodic_canary()
            except Exception:  # noqa: BLE001
                logger.exception("quality monitor sweep failed")

    def _periodic_canary(self) -> None:
        known = set(self.sentinel.tenants())
        for model_id, model in self._active_models():
            if model is None:
                continue
            if model_id not in known:
                # registered before the server attached (no swap-check
                # hook ran): adopt a baseline so the NEXT sweep/swap has
                # something to drift against
                self.sentinel.capture(model_id, model)
                continue
            verdict = self.sentinel.replay(model_id, model)
            if verdict is not None and self._m_canary is not None:
                self._m_canary.set(verdict["drift"],
                                   model=self.label(model_id))

    # -- /qualityz ------------------------------------------------------ #

    def qualityz_payload(self, query_params: Optional[Dict] = None
                         ) -> Tuple[str, bytes]:
        audit = self.auditor.snapshot()
        with self._audit_lock:
            audit["backlog"] = len(self._audit_queue)
            audit["backlog_dropped"] = self._audit_dropped
        doc = {
            "component": "server",
            "audit": audit,
            "shadow": self.sampler.snapshot(),
            "canary": self.sentinel.snapshot(),
        }
        return "application/json", json.dumps(doc).encode("utf-8")


def stub_doc(component: str = "proxy") -> Dict:
    """The empty ``/qualityz`` document for components that serve the
    endpoint but audit nothing themselves (the fan-in proxy without
    ``?federate=1``)."""

    return {
        "component": component,
        "audit": {"enabled": False, "audited_total": 0,
                  "violation_answers_total": 0, "backlog": 0,
                  "backlog_dropped": 0, "ring_size": 0, "ring": []},
        "shadow": {"fraction": 0.0, "budget_s": 0.0, "spent_s": 0.0,
                   "max_run_s": 0.0, "exhausted": False, "offered": 0,
                   "sampled": 0, "dropped": 0, "queued": 0, "tenants": {}},
        "canary": {"threshold": DRIFT_TOLERANCE, "tenants": {}},
    }


def merge_quality_pages(pages: List[str]) -> str:
    """Fold per-replica ``/qualityz`` JSON pages into one fleet view
    (the proxy's ``?federate=1`` answer, same contract as the profiler's
    flamegraph fold): counters sum, repro rings concatenate newest-first
    under the ring bound, per-tenant shadow/canary sections keep the
    worst (max) error and sum run counts."""

    merged = stub_doc("fleet")
    merged["replicas"] = 0
    ring: List[Dict] = []
    audit, shadow, canary = (merged["audit"], merged["shadow"],
                             merged["canary"])
    for page in pages:
        try:
            doc = json.loads(page)
        except (ValueError, TypeError):
            continue
        merged["replicas"] += 1
        a = doc.get("audit", {})
        audit["enabled"] = audit["enabled"] or bool(a.get("enabled"))
        audit["audited_total"] += int(a.get("audited_total", 0))
        audit["violation_answers_total"] += \
            int(a.get("violation_answers_total", 0))
        audit["backlog"] += int(a.get("backlog", 0))
        audit["backlog_dropped"] += int(a.get("backlog_dropped", 0))
        audit["ring_size"] = max(audit["ring_size"],
                                 int(a.get("ring_size", 0)))
        ring.extend(a.get("ring", []))
        s = doc.get("shadow", {})
        for key in ("spent_s", "budget_s", "fraction"):
            shadow[key] += float(s.get(key, 0.0))
        shadow["max_run_s"] = max(shadow["max_run_s"],
                                  float(s.get("max_run_s", 0.0)))
        for key in ("offered", "sampled", "dropped", "queued"):
            shadow[key] += int(s.get(key, 0))
        shadow["exhausted"] = shadow["exhausted"] or bool(s.get("exhausted"))
        for mid, t in (s.get("tenants") or {}).items():
            agg = shadow["tenants"].setdefault(
                mid, {"runs": 0, "last_err": None, "series": []})
            agg["runs"] += int(t.get("runs", 0))
            err = t.get("last_err")
            if err is not None:
                agg["last_err"] = err if agg["last_err"] is None \
                    else max(agg["last_err"], err)
            agg["series"].extend(t.get("series", []))
        c = doc.get("canary", {})
        canary["threshold"] = max(canary["threshold"],
                                  float(c.get("threshold", 0.0)))
        for mid, t in (c.get("tenants") or {}).items():
            prev = canary["tenants"].get(mid)
            if prev is None or (t.get("drift") or 0.0) >= \
                    (prev.get("drift") or 0.0):
                canary["tenants"][mid] = t
    ring.sort(key=lambda e: e.get("ts", 0.0), reverse=True)
    bound = audit["ring_size"] or DEFAULT_RING
    audit["ring"] = ring[:bound]
    for agg in shadow["tenants"].values():
        agg["series"] = sorted(agg["series"])[-DEFAULT_SERIES:]
    return json.dumps(merged)
