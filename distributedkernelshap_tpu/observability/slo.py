"""Declarative SLOs evaluated as multi-window multi-burn-rate conditions
over the time-series store.

The paper's whole claim is a latency/throughput number, and the serving
stack's scheduler already *admits* by declared SLO (priority class +
deadline, ``scheduling/``) — but nothing could say "the interactive SLO
is burning" after the fact.  This module is the interpretation layer:
an :class:`SLO` names a target ("99% of requests succeed", "90% of
interactive requests finish under 500 ms", "work in flight never stalls
longer than 30 s") and evaluates it the way Google SRE burn-rate alerts
do (SRE Workbook ch. 5): the **burn rate** over a window is

    bad_fraction(window) / (1 - target)

i.e. how many times faster than "exactly on budget" the error budget is
being spent.  A condition holds when the burn rate exceeds a factor in a
long window AND in a short window (:class:`BurnRateWindow`): the long
window proves the problem is sustained, the short window makes the alert
resolve promptly once the problem stops.  Multiple window pairs express
the page/ticket split; any breached pair marks the SLO breached.

Three SLO kinds, matching what the serving stack can measure:

* :class:`AvailabilitySLO` — two counters (total, bad); bad fraction is
  ``delta(bad)/delta(total)`` over the window.
* :class:`LatencySLO` — a histogram + threshold; bad fraction is
  ``1 - frac_le(threshold)`` over the window's bucket increments.  The
  per-priority-class server SLOs are this over
  ``dks_serve_class_latency_seconds{class=...}``.
* :class:`StalenessSLO` — a gauge + bound; bad fraction is the fraction
  of window samples above the bound (e.g. seconds since in-flight work
  last progressed).

``evaluate`` returns ``None`` burn rates when the window holds no data —
an idle server is not in breach, and an alert must not fire on silence.

Stdlib-only, no imports from the serving stack: targets reference metric
*names*, resolved against whatever store the health engine samples into.
"""

import logging
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

#: the scheduler's priority classes, restated here (importing
#: ``scheduling`` would drag numpy into the stdlib-only observability
#: package); ``tests/test_slo_alerts.py`` asserts the two stay in sync
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")


class BurnRateWindow(NamedTuple):
    """One multi-window condition: burn >= ``factor`` over BOTH windows."""

    long_s: float
    short_s: float
    factor: float


#: default page condition: a 5-minute window burning 6x budget, confirmed
#: by the last 30 s (resolves within ~30 s of the problem stopping)
DEFAULT_WINDOWS = (BurnRateWindow(long_s=300.0, short_s=30.0, factor=6.0),)


class SLO:
    """Base: a named target plus its burn-rate windows.  Subclasses
    implement :meth:`bad_fraction` over the store."""

    kind = "slo"

    def __init__(self, name: str, target: float,
                 windows: Sequence[BurnRateWindow] = DEFAULT_WINDOWS,
                 description: str = ""):
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        self.name = name
        self.target = float(target)
        self.windows = tuple(BurnRateWindow(*w) for w in windows)
        if not self.windows:
            raise ValueError("an SLO needs at least one burn-rate window")
        self.description = description

    # -- subclass hook -------------------------------------------------- #

    def bad_fraction(self, store, window_s: float,
                     now: Optional[float] = None) -> Optional[float]:
        raise NotImplementedError

    # -- evaluation ------------------------------------------------------ #

    def burn_rate(self, store, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        bad = self.bad_fraction(store, window_s, now=now)
        if bad is None:
            return None
        return bad / max(1e-9, 1.0 - self.target)

    def evaluate(self, store, now: Optional[float] = None) -> Dict:
        """One status dict: per-window burn rates, breached flag, budget
        remaining over the longest window (1.0 = untouched, 0.0 = spent
        exactly, negative = overspent)."""

        burn_rates: Dict[str, Optional[float]] = {}
        breached = False
        for w in self.windows:
            b_long = self.burn_rate(store, w.long_s, now=now)
            b_short = self.burn_rate(store, w.short_s, now=now)
            burn_rates[f"{w.long_s:g}s"] = b_long
            burn_rates[f"{w.short_s:g}s"] = b_short
            if (b_long is not None and b_short is not None
                    and b_long >= w.factor and b_short >= w.factor):
                breached = True
        longest = max(w.long_s for w in self.windows)
        bad = self.bad_fraction(store, longest, now=now)
        budget_remaining = (None if bad is None
                            else 1.0 - bad / max(1e-9, 1.0 - self.target))
        return {"name": self.name, "kind": self.kind,
                "target": self.target,
                "description": self.description,
                "windows": [list(w) for w in self.windows],
                "burn_rates": burn_rates,
                "budget_remaining": budget_remaining,
                "breached": breached}


class AvailabilitySLO(SLO):
    """``target`` fraction of requests answered without error, from two
    cumulative counters (optionally labelled)."""

    kind = "availability"

    def __init__(self, name: str, total: str, bad: str, target: float,
                 total_labels: Optional[Dict[str, str]] = None,
                 bad_labels: Optional[Dict[str, str]] = None, **kwargs):
        super().__init__(name, target, **kwargs)
        self.total = total
        self.bad = bad
        self.total_labels = total_labels
        self.bad_labels = bad_labels

    def bad_fraction(self, store, window_s, now=None):
        total = store.delta(self.total, window_s, self.total_labels, now=now)
        if total is None or total <= 0:
            return None  # no traffic in the window: nothing burned
        bad = store.delta(self.bad, window_s, self.bad_labels, now=now) or 0.0
        return max(0.0, min(1.0, bad / total))


class LatencySLO(SLO):
    """``target`` fraction of observations at or under ``threshold_s``,
    from a histogram's windowed bucket increments."""

    kind = "latency"

    def __init__(self, name: str, histogram: str, threshold_s: float,
                 target: float, labels: Optional[Dict[str, str]] = None,
                 **kwargs):
        super().__init__(name, target, **kwargs)
        self.histogram = histogram
        self.threshold_s = float(threshold_s)
        self.labels = labels

    def bad_fraction(self, store, window_s, now=None):
        good = store.frac_le(self.histogram, self.threshold_s, window_s,
                             self.labels, now=now)
        if good is None:
            return None
        return max(0.0, min(1.0, 1.0 - good))


class ErrorBudgetSLO(LatencySLO):
    """``target`` fraction of anytime answers whose *reported* final
    error bound is at or under ``max_err`` — the accuracy analogue of a
    latency SLO, burned over the ``dks_anytime_final_err`` histogram.
    Mechanically identical to :class:`LatencySLO` (a histogram and a
    threshold); the separate kind keeps /slo output honest about what is
    being promised: answer *quality* under deadline pressure, not answer
    time."""

    kind = "error_budget"

    def __init__(self, name: str, histogram: str, max_err: float,
                 target: float, labels: Optional[Dict[str, str]] = None,
                 **kwargs):
        super().__init__(name, histogram=histogram, threshold_s=max_err,
                         target=target, labels=labels, **kwargs)

    @property
    def max_err(self) -> float:
        return self.threshold_s


class StalenessSLO(SLO):
    """``target`` fraction of window samples where a gauge stays at or
    under ``max_staleness_s`` (e.g. seconds since in-flight work last
    progressed — the watchdog's view, made continuous)."""

    kind = "staleness"

    def __init__(self, name: str, gauge: str, max_staleness_s: float,
                 target: float, labels: Optional[Dict[str, str]] = None,
                 **kwargs):
        super().__init__(name, target, **kwargs)
        self.gauge = gauge
        self.max_staleness_s = float(max_staleness_s)
        self.labels = labels

    def bad_fraction(self, store, window_s, now=None):
        return store.frac_over(self.gauge, window_s, self.max_staleness_s,
                               self.labels, now=now)


class QualitySLO(SLO):
    """``target`` fraction of audited answers passing the in-band
    invariant screen (``observability/quality.py``): burned from the
    windowed increments of ``dks_quality_violations_total`` (summed
    across its ``{model, path, check}`` labelsets — the store's delta is
    an exact-labelset lookup, so the fleet total is folded here) over
    the unlabeled ``dks_quality_audited_total``.  Burns only when
    audited traffic flows; with the auditor off this SLO is inert."""

    kind = "quality"

    def __init__(self, name: str,
                 violations: str = "dks_quality_violations_total",
                 audited: str = "dks_quality_audited_total",
                 target: float = 0.999, **kwargs):
        super().__init__(name, target, **kwargs)
        self.violations = violations
        self.audited = audited

    def bad_fraction(self, store, window_s, now=None):
        total = store.delta(self.audited, window_s, now=now)
        if total is None or total <= 0:
            return None  # nothing audited in the window: nothing burned
        bad = 0.0
        for labels in store.labelsets(self.violations):
            bad += store.delta(self.violations, window_s, labels,
                               now=now) or 0.0
        return max(0.0, min(1.0, bad / total))


# --------------------------------------------------------------------- #
# default SLO sets for the two serving components
# --------------------------------------------------------------------- #

#: per-class latency thresholds/targets for the scheduler's priority
#: classes — interactive is the paper's human-in-the-loop case, batch
#: tracks the pool benchmark envelope, best_effort only promises
#: eventual completion.  Every threshold MUST be at or below the latency
#: histogram's largest finite bucket (serving LATENCY_BUCKETS_S tops out
#: at 60 s): observations land in buckets, so a threshold beyond the
#: last bound would count every +Inf observation as a violation even
#: when it actually met the SLO.
CLASS_LATENCY_TARGETS: Dict[str, Tuple[float, float]] = {
    "interactive": (0.5, 0.90),
    "batch": (30.0, 0.90),
    "best_effort": (60.0, 0.50),
}

#: default anytime error-budget objective: 90% of anytime answers must
#: report a final error bound at or under 0.03 — aligned with a finite
#: ``dks_anytime_final_err`` bucket bound (3e-2) for the same reason the
#: latency thresholds align with LATENCY_BUCKETS_S: observations land in
#: buckets, and a threshold between bounds would miscount the straddling
#: bucket.  Burns only when anytime traffic flows (idle = None = no
#: breach), so non-anytime deployments carry this SLO inert.
ANYTIME_ERR_TARGET: Tuple[float, float] = (0.03, 0.90)

#: default answer-quality objective: 99.9% of audited answers must pass
#: the invariant screen.  The screen's tolerances are path-calibrated
#: (``quality.PATH_TOLERANCES``), so a healthy fleet sits at zero
#: violations — any sustained burn here is a real correctness incident
#: (device fault, engine regression, bad swap), not estimator variance.
QUALITY_TARGET: float = 0.999

#: default per-tenant objectives (the templated SLOs of
#: :func:`tenant_slos`): latency over ``dks_tenant_latency_seconds`` —
#: threshold must stay at or below that histogram's largest finite
#: bucket, same contract as CLASS_LATENCY_TARGETS — and availability
#: over the tenant request/error counter pair
TENANT_LATENCY_TARGET: Tuple[float, float] = (0.5, 0.90)
TENANT_AVAILABILITY_TARGET: float = 0.99

#: bounded-cardinality guard on SLO templating: each tenant adds two
#: SLOs (and two derived burn-rate rules + four dks_slo_* gauge series),
#: all re-evaluated per health tick — a tenant flood must not turn the
#: sampler tick into an O(tenants x windows) ring scan storm.  Tenants
#: past the cap get no per-tenant SLO (logged once per refresh); the
#: fleet-level class SLOs still cover their traffic.
MAX_TENANT_SLOS = 32


def tenant_slos(tenants: Sequence,
                windows: Sequence[BurnRateWindow] = DEFAULT_WINDOWS,
                latency_target: Tuple[float, float] = TENANT_LATENCY_TARGET,
                availability_target: float = TENANT_AVAILABILITY_TARGET,
                max_tenants: int = MAX_TENANT_SLOS) -> List[SLO]:
    """Template per-tenant latency + availability objectives over the
    cost meter's tenant families.  ``tenants`` holds model ids (or
    ``(model_id, version)`` pairs — the version only names the SLO; the
    underlying series are per-model, so a hot-swap keeps burning against
    one history).  Bounded by ``max_tenants`` (see MAX_TENANT_SLOS)."""

    slos: List[SLO] = []
    seen = set()
    for entry in tenants:
        if isinstance(entry, (tuple, list)):
            model_id, version = entry[0], entry[1]
            label = f"{model_id}@v{version}"
        else:
            model_id, label = str(entry), str(entry)
        if model_id in seen:
            continue
        seen.add(model_id)
        if len(slos) // 2 >= max_tenants:
            logger.warning(
                "tenant SLO cap (%d) reached; %r (and later tenants) get "
                "no per-tenant SLO — fleet-level class SLOs still apply",
                max_tenants, model_id)
            break
        threshold_s, target = latency_target
        slos.append(LatencySLO(
            f"tenant:{model_id}_latency",
            histogram="dks_tenant_latency_seconds",
            labels={"model": model_id}, threshold_s=threshold_s,
            target=target, windows=windows,
            description=f"tenant {label} requests finishing within "
                        f"{threshold_s:g}s"))
        slos.append(AvailabilitySLO(
            f"tenant:{model_id}_availability",
            total="dks_tenant_requests_total",
            bad="dks_tenant_errors_total",
            total_labels={"model": model_id},
            bad_labels={"model": model_id},
            target=availability_target, windows=windows,
            description=f"tenant {label} answered requests that are "
                        f"not errors"))
    return slos


def default_server_slos(
        windows: Sequence[BurnRateWindow] = DEFAULT_WINDOWS,
        tenants: Sequence = ()) -> List[SLO]:
    """The server's standard SLO set: availability, one latency SLO per
    priority class (over ``dks_serve_class_latency_seconds``), an
    in-flight staleness SLO feeding off the watchdog's progress gauge,
    and — multi-tenant gateways — per-tenant latency/availability
    objectives templated for every id in ``tenants`` (bounded; see
    :func:`tenant_slos`).  The server refreshes the tenant portion on
    registry hot-swap/removal so stale tenants stop being evaluated."""

    slos: List[SLO] = [
        AvailabilitySLO(
            "availability", total="dks_serve_requests_total",
            bad="dks_serve_errors_total", target=0.99, windows=windows,
            description="answered requests that are not errors"),
    ]
    for klass in PRIORITY_CLASSES:
        threshold_s, target = CLASS_LATENCY_TARGETS[klass]
        slos.append(LatencySLO(
            f"{klass}_latency",
            histogram="dks_serve_class_latency_seconds",
            labels={"class": klass}, threshold_s=threshold_s, target=target,
            windows=windows,
            description=f"{klass} requests finishing within "
                        f"{threshold_s:g}s"))
    slos.append(StalenessSLO(
        "inflight_progress", gauge="dks_serve_last_progress_age_seconds",
        max_staleness_s=30.0, target=0.90, windows=windows,
        description="dispatched work progressing within 30s"))
    max_err, target = ANYTIME_ERR_TARGET
    slos.append(ErrorBudgetSLO(
        "anytime_error", histogram="dks_anytime_final_err",
        max_err=max_err, target=target, windows=windows,
        description=f"anytime answers with a final reported error bound "
                    f"at or under {max_err:g}"))
    slos.append(QualitySLO(
        "answer_quality", target=QUALITY_TARGET, windows=windows,
        description="audited answers passing the invariant screen "
                    "(additivity, finiteness, error-bound sanity)"))
    if tenants:
        slos.extend(tenant_slos(tenants, windows=windows))
    return slos


def default_proxy_slos(
        windows: Sequence[BurnRateWindow] = DEFAULT_WINDOWS) -> List[SLO]:
    """The fan-in proxy's standard SLO set: forwarded-request
    availability (replica mid-request failures are the bad events)."""

    return [
        AvailabilitySLO(
            "proxy_availability", total="dks_fanin_forwarded_total",
            bad="dks_fanin_replica_errors_total", target=0.99,
            windows=windows,
            description="forwarded requests not lost to replica failures"),
    ]
