"""The health engine behind ``/statusz``: one object bundling the
time-series sampler, the SLO set and the alert manager for a serving
component.

``ExplainerServer`` and ``FanInProxy`` each own a
:class:`HealthEngine` next to their ``MetricsRegistry``.  The engine

* samples the registry into a bounded :class:`~distributedkernelshap_tpu.
  observability.timeseries.TimeSeriesStore` on a fixed interval (one
  daemon thread per component; ``interval_s=0`` disables sampling but
  keeps the page serving — a cold ``/statusz`` must render);
* evaluates the component's SLOs and alert rules on the same tick, so
  alert latency is exactly one sampling interval;
* registers the health series back into the registry —
  ``dks_slo_budget_remaining{slo=}``, ``dks_slo_burn_rate{slo=,window=}``
  and (via the alert manager) ``dks_alerts_firing{rule=}`` — so ordinary
  scrapers see SLO state without speaking a second protocol;
* assembles the ``/statusz`` payload: SLO status, alert states, recent
  flight-recorder timeline, sparkline series, component-specific detail
  (queue depths / replica liveness) — one human page
  (:func:`render_statusz_html`) and one machine schema
  (``?format=json``, stable keys asserted by ``tests/test_statusz.py``).

Stdlib-only, like everything under ``observability/``.
"""

import html
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from distributedkernelshap_tpu.observability.alerts import (
    AlertManager,
    FlightRecorderSink,
    LogSink,
    WebhookSink,
    slo_burn_rule,
)
from distributedkernelshap_tpu.analysis import lockwitness
from distributedkernelshap_tpu.observability.timeseries import (
    RegistrySampler,
    TimeSeriesStore,
    sparkline,
)

logger = logging.getLogger(__name__)

#: flight-recorder tail length on the page
_FLIGHTREC_TAIL = 20

#: sparkline points rendered per series
_SPARK_POINTS = 60


class HealthEngine:
    """Sampler + SLOs + alerts for one component (see module doc).

    Parameters
    ----------
    registry
        The component's :class:`MetricsRegistry` — sampled into the store
        and extended with the ``dks_slo_*`` / ``dks_alerts_firing``
        series.
    component
        ``"server"`` or ``"proxy"`` (labels log lines, flight-recorder
        events and the page header).
    slos
        The SLO set to evaluate (e.g. ``slo.default_server_slos()``).
    rules
        Alert rules.  ``None`` derives one burn-rate rule per SLO via
        :func:`~distributedkernelshap_tpu.observability.alerts.
        slo_burn_rule`; pass an explicit list (possibly empty) to
        override.
    sinks
        Alert sinks.  ``None`` means log + flight recorder (+ webhook
        when ``webhook_url`` is set).
    interval_s
        Sampling/evaluation period; ``0`` disables the background thread
        (the store then only moves on explicit :meth:`tick` calls).
    spark_names
        Metric names surfaced as sparklines on the page (counters render
        as per-interval rates, gauges as levels).
    """

    def __init__(self, registry, component: str, slos: Sequence = (),
                 rules: Optional[Sequence] = None,
                 sinks: Optional[Sequence] = None,
                 flight=None, interval_s: float = 1.0,
                 store: Optional[TimeSeriesStore] = None,
                 capacity: int = 600,
                 webhook_url: Optional[str] = None,
                 spark_names: Sequence[str] = ()):
        if flight is None:
            from distributedkernelshap_tpu.observability.flightrec import (
                flightrec,
            )

            flight = flightrec()
        self.component = component
        self.flight = flight
        self.registry = registry
        self.slos = list(slos)
        self.store = store if store is not None else TimeSeriesStore(capacity)
        self.interval_s = float(interval_s)
        self.sampler = RegistrySampler(self.store, [registry],
                                       interval_s=self.interval_s)
        # whether the rule set is DERIVED from the SLOs (one burn rule
        # each): only then may set_slos rebuild it — an explicit rules
        # override is the caller's contract and stays put
        self._rules_derived = rules is None
        if rules is None:
            rules = [slo_burn_rule(slo) for slo in self.slos]
        if sinks is None:
            sinks = [LogSink(), FlightRecorderSink(flight)]
            if webhook_url:
                sinks.append(WebhookSink(webhook_url))
        self.alerts = AlertManager(self.store, rules, sinks=sinks,
                                   component=component)
        self.spark_names = tuple(spark_names)
        self.started_at = time.time()
        # SLO-status memo: the two dks_slo_* gauge callbacks fire on
        # every scrape AND every sampler tick (collect() samples them
        # too), and each evaluation is an O(window) ring scan per SLO —
        # a short TTL collapses the per-tick repeats into one pass.
        # Half the sampling interval (capped) so a cached status never
        # spans two ticks even at sub-second intervals.
        self._status_ttl_s = (min(0.5, self.interval_s / 2)
                              if self.interval_s > 0 else 0.5)
        self._status_cache: tuple = (0.0, None)
        self._status_lock = lockwitness.make_lock("statusz.status")
        # logical evaluation time for deterministic tick(now=...): the
        # registry's dks_slo_* gauge callbacks take no arguments, so a
        # replayed tick routes its timestamp here — without it the
        # callbacks would evaluate at wall time over logically-stamped
        # samples and record full-budget gauges during a replayed burn
        self._eval_now: Optional[float] = None
        self._register_metrics(registry)

    # -- registry back-channel ------------------------------------------ #

    def _register_metrics(self, registry) -> None:
        self.alerts.attach_metrics(registry)
        registry.gauge(
            "dks_slo_budget_remaining",
            "Error-budget fraction left over the SLO's longest window "
            "(1 = untouched, <0 = overspent).",
            labelnames=("slo",)).set_function(self._budget_series)
        registry.gauge(
            "dks_slo_burn_rate",
            "Error-budget burn rate by SLO and window (1 = spending "
            "exactly on budget).",
            labelnames=("slo", "window")).set_function(self._burn_series)

    def _statuses(self, now: Optional[float] = None) -> List[Dict]:
        if now is None:
            now = (self._eval_now if self._eval_now is not None
                   else time.time())
        with self._status_lock:
            cached_at, cached = self._status_cache
            if cached is not None and 0 <= now - cached_at < \
                    self._status_ttl_s:
                return cached
        statuses = [slo.evaluate(self.store, now=now) for slo in self.slos]
        with self._status_lock:
            self._status_cache = (now, statuses)
        return statuses

    def slo_statuses(self, now: Optional[float] = None) -> List[Dict]:
        """Public view of the current SLO evaluations (memoised per tick
        like the gauge callbacks) — the autoscaler reads its burn-rate
        and budget-remaining signals from here instead of re-deriving
        them from the store."""

        return self._statuses(now)

    def set_slos(self, slos: Sequence) -> None:
        """Replace the evaluated SLO set at runtime (the server's
        per-tenant SLO refresh on registry hot-swap/removal).  When the
        alert rules were derived from the SLOs, they are rebuilt to
        match — rules whose name survives keep their alert state (see
        ``AlertManager.set_rules``); an explicit ``rules`` override is
        left untouched.  The status memo is invalidated so the next
        scrape/tick evaluates the new set."""

        self.slos = list(slos)
        if self._rules_derived:
            self.alerts.set_rules([slo_burn_rule(slo) for slo in self.slos])
        with self._status_lock:
            self._status_cache = (0.0, None)

    def _budget_series(self) -> Dict[tuple, float]:
        out = {}
        for status in self._statuses():
            remaining = status["budget_remaining"]
            # an idle window (no data) reports a full budget: silence is
            # not an outage
            out[(status["name"],)] = 1.0 if remaining is None else remaining
        return out

    def _burn_series(self) -> Dict[tuple, float]:
        out = {}
        for status in self._statuses():
            for window, burn in status["burn_rates"].items():
                out[(status["name"], window)] = 0.0 if burn is None else burn
        return out

    # -- lifecycle ------------------------------------------------------- #

    def tick(self, now: Optional[float] = None) -> List[Dict]:
        """One deterministic sample+evaluate step (tests, replays);
        returns the alert transitions it caused.  ``now`` also becomes
        the gauge callbacks' evaluation time for the duration of the
        tick, so replayed dks_slo_* samples reflect the logical clock."""

        self._eval_now = now
        try:
            self.sampler.sample_once(now=now)
            return self.alerts.evaluate(now=now)
        finally:
            self._eval_now = None

    def start(self) -> "HealthEngine":
        self.started_at = time.time()
        self.sampler.start(on_tick=self.alerts.evaluate)
        return self

    def stop(self) -> None:
        self.sampler.stop()

    # -- /statusz -------------------------------------------------------- #

    def _series_payload(self, now: float) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for name, labels in self.store.series_keys():
            if name not in self.spark_names:
                continue
            kind = self.store.kind(name, labels)
            if kind == "histogram":
                continue
            if kind == "counter":
                pts = self.store.rate_points(name, labels)[-_SPARK_POINTS:]
            else:
                pts = self.store.points(name, labels)[-_SPARK_POINTS:]
            label_str = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            key = f"{name}{{{label_str}}}" if label_str else name
            values = [v for _, v in pts]
            out[key] = {
                "kind": "rate" if kind == "counter" else "level",
                "latest": round(values[-1], 6) if values else None,
                "points": [[round(t, 3), round(v, 6)] for t, v in pts],
                "sparkline": sparkline(values),
            }
        return out

    def statusz_payload(self, detail: Optional[Dict] = None) -> Dict:
        """The stable ``/statusz?format=json`` document."""

        now = time.time()
        alerts = self.alerts.payload(now=now)
        slos = self._statuses(now)
        firing = [a for a in alerts["alerts"] if a["state"] == "firing"]
        return {
            "component": self.component,
            "generated_at": now,
            "uptime_s": round(now - self.started_at, 1),
            "healthy": not any(a["severity"] == "page" for a in firing),
            "sampler": {
                "interval_s": self.interval_s,
                "enabled": self.interval_s > 0,
                "samples_taken": self.sampler.samples_taken,
                "series": len(self.store.series_keys()),
                "store_capacity": self.store.capacity,
            },
            "slos": slos,
            "alerts": alerts["alerts"],
            "silences": alerts["silences"],
            "series": self._series_payload(now),
            "flightrec": self.flight.snapshot()[-_FLIGHTREC_TAIL:],
            "detail": dict(detail or {}),
        }


# --------------------------------------------------------------------- #
# human rendering
# --------------------------------------------------------------------- #

_STATE_COLORS = {"firing": "#c0392b", "pending": "#e67e22",
                 "inactive": "#27ae60"}

_CSS = """
body { font-family: monospace; margin: 1.5em; color: #222; }
h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.4em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #ccc; padding: 2px 8px; text-align: left; }
th { background: #f0f0f0; }
.spark { font-size: 1.1em; letter-spacing: 1px; }
.muted { color: #888; }
"""


def _fmt(value, digits=3) -> str:
    if value is None:
        return "–"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        return f"{value:.{digits}f}"
    return str(value)


def render_statusz_html(payload: Dict) -> str:
    """One human-readable page from the JSON payload — everything the
    JSON carries, nothing it does not (the page can never show state the
    machine schema omits)."""

    e = html.escape
    p = payload
    rows: List[str] = []
    rows.append(f"<!doctype html><html><head><title>/statusz — "
                f"{e(p['component'])}</title><style>{_CSS}</style></head>"
                f"<body>")
    health = "HEALTHY" if p["healthy"] else "UNHEALTHY"
    color = "#27ae60" if p["healthy"] else "#c0392b"
    rows.append(f"<h1>{e(p['component'])} /statusz — "
                f"<span style='color:{color}'>{health}</span></h1>")
    sampler = p["sampler"]
    rows.append(
        f"<p class='muted'>uptime {p['uptime_s']:.0f}s · sampler "
        f"{'on' if sampler['enabled'] else 'OFF'} "
        f"(interval {sampler['interval_s']:g}s, "
        f"{sampler['samples_taken']} samples, {sampler['series']} series) · "
        f"generated {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(p['generated_at']))}Z"
        f" · <a href='/statusz?format=json'>json</a> · "
        f"<a href='/metrics'>metrics</a> · <a href='/debugz'>debugz</a></p>")

    rows.append("<h2>SLOs</h2>")
    if p["slos"]:
        rows.append("<table><tr><th>slo</th><th>kind</th><th>target</th>"
                    "<th>budget remaining</th><th>burn rates</th>"
                    "<th>breached</th></tr>")
        for s in p["slos"]:
            burns = " ".join(
                f"{w}:{_fmt(b, 2)}" for w, b in sorted(s["burn_rates"].items()))
            style = " style='color:#c0392b'" if s["breached"] else ""
            rows.append(
                f"<tr{style}><td>{e(s['name'])}</td><td>{e(s['kind'])}</td>"
                f"<td>{s['target']:g}</td>"
                f"<td>{_fmt(s['budget_remaining'], 3)}</td>"
                f"<td>{e(burns)}</td><td>{_fmt(s['breached'])}</td></tr>")
        rows.append("</table>")
    else:
        rows.append("<p class='muted'>no SLOs configured</p>")

    rows.append("<h2>Alerts</h2>")
    if p["alerts"]:
        rows.append("<table><tr><th>rule</th><th>state</th>"
                    "<th>severity</th><th>since</th><th>info</th></tr>")
        for a in p["alerts"]:
            color = _STATE_COLORS.get(a["state"], "#222")
            since = f"{a['since_s']:.0f}s" if a["since_s"] is not None else "–"
            rows.append(
                f"<tr><td>{e(a['rule'])}</td>"
                f"<td style='color:{color}'>{e(a['state'])}</td>"
                f"<td>{e(a['severity'])}</td><td>{since}</td>"
                f"<td class='muted'>{e(json.dumps(a['info'], default=repr)[:200])}"
                f"</td></tr>")
        rows.append("</table>")
    else:
        rows.append("<p class='muted'>no alert rules configured</p>")
    if p["silences"]:
        rows.append("<p>silences: " + ", ".join(
            f"{e(s['pattern'])} ({s['expires_in_s']:.0f}s left)"
            for s in p["silences"]) + "</p>")

    if p["detail"]:
        rows.append("<h2>Component detail</h2><table>")
        for key, value in sorted(p["detail"].items()):
            rows.append(f"<tr><th>{e(str(key))}</th><td>"
                        f"{e(json.dumps(value, default=repr)[:500])}"
                        f"</td></tr>")
        rows.append("</table>")

    rows.append("<h2>Recent series</h2>")
    if p["series"]:
        rows.append("<table><tr><th>series</th><th>view</th>"
                    "<th>latest</th><th>recent</th></tr>")
        for name, s in sorted(p["series"].items()):
            rows.append(
                f"<tr><td>{e(name)}</td><td>{e(s['kind'])}</td>"
                f"<td>{_fmt(s['latest'])}</td>"
                f"<td class='spark'>{e(s['sparkline'])}</td></tr>")
        rows.append("</table>")
    else:
        rows.append("<p class='muted'>no samples yet (cold start or "
                    "sampler disabled)</p>")

    rows.append("<h2>Recent events (flight recorder)</h2>")
    if p["flightrec"]:
        rows.append("<table><tr><th>seq</th><th>age</th><th>kind</th>"
                    "<th>fields</th></tr>")
        for ev in reversed(p["flightrec"]):
            extra = {k: v for k, v in ev.items()
                     if k not in ("ts", "seq", "kind")}
            age = p["generated_at"] - ev["ts"]
            rows.append(
                f"<tr><td>{ev['seq']}</td><td>{age:.1f}s</td>"
                f"<td>{e(ev['kind'])}</td><td class='muted'>"
                f"{e(json.dumps(extra, default=repr)[:200])}</td></tr>")
        rows.append("</table>")
    else:
        rows.append("<p class='muted'>no events recorded</p>")
    rows.append("</body></html>")
    return "\n".join(rows)


def statusz_response(engine: HealthEngine, query: str,
                     detail: Optional[Dict] = None):
    """Shared handler body for both components' ``/statusz`` routes:
    returns ``(content_type, body_str)`` honouring ``?format=json``."""

    payload = engine.statusz_payload(detail=detail)
    wants_json = "format=json" in (query or "")
    if wants_json:
        return ("application/json",
                json.dumps(payload, default=repr))
    return "text/html; charset=utf-8", render_statusz_html(payload)
