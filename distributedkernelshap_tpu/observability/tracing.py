"""Dependency-free distributed tracing with ``X-DKS-Trace`` propagation.

The reference measures wall-clock only around whole ``explain`` calls
(SURVEY §5.1); after the scheduling and resilience PRs there is no way to
answer "where did request X spend its 400 ms" across the client retry /
proxy hedge / replica admission→queue→device→finalize path.  This module
is the substrate: spans are plain records (name, trace id, span id,
parent id, wall-clock start, duration, attributes) collected in a bounded
in-process ring buffer, exported as JSONL, and convertible to the
Chrome/Perfetto ``trace_event`` format for flamegraph viewing.

**Context propagation** is W3C-traceparent-shaped over one header::

    X-DKS-Trace: 00-<32 hex trace id>-<16 hex span id>-01

The client mints the trace id; the fan-in proxy parents its request span
to the client's, gives every routing pass (primary / hedge) and every
forward attempt its OWN span id, and stamps the forward span's context
onto the header it sends the replica — so a replica's spans parent to the
exact pass (hedged or not, retried or not) that reached it.  Everything
in one trace shares the trace id; JSONL consumers follow a request
end-to-end by filtering on it.

**Time base**: span ``ts`` is epoch seconds (comparable across the
client/proxy/replica processes of one host), durations are measured on
the monotonic clock.  Cross-host skew is the operator's problem, as with
any distributed tracer.

**Cost when disabled** (the default): one attribute read per guard —
every producer checks ``tracer().enabled`` before building anything.

Enable with ``DKS_TRACE=1`` (or ``tracer().enable()``).  With
``DKS_TRACE_DIR`` set, every finished span is ALSO appended (flushed) to
``<dir>/spans-<pid>.jsonl`` — that is how replica worker processes get
their spans into the chaos bench's merged trace even when they are
SIGKILLed mid-run.
"""

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Union

logger = logging.getLogger(__name__)

TRACE_HEADER = "X-DKS-Trace"

#: epoch <-> monotonic alignment, fixed at import so every span in a
#: process shares one offset (a per-call offset would let spans within
#: one request disagree by scheduler jitter)
_EPOCH_OFFSET = time.time() - time.monotonic()


def mono_to_epoch(t_mono: float) -> float:
    return t_mono + _EPOCH_OFFSET


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str


def format_trace_header(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_trace_header(value: Optional[str]) -> Optional[SpanContext]:
    """Parse ``X-DKS-Trace``; accepts the full ``00-trace-span-flags``
    form and the bare ``trace-span`` form.  Garbage returns ``None`` —
    an unparseable header must degrade to "start a new trace", never to
    a 400."""

    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) == 4:
        parts = parts[1:3]
    if len(parts) != 2:
        return None
    trace_id, span_id = parts
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())


def header_get(headers, name: str = TRACE_HEADER) -> Optional[str]:
    """Case-insensitive header lookup over a plain dict (the proxy hands
    handlers dicts, not Message objects)."""

    if headers is None:
        return None
    target = name.lower()
    for k, v in headers.items():
        if k.lower() == target:
            return v
    return None


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "ts",
                 "duration_s", "attrs", "proc", "thread", "_t0_mono")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], ts: float, duration_s: float,
                 attrs: Optional[Dict] = None, proc: str = "",
                 thread: int = 0):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts = ts              # epoch seconds
        self.duration_s = duration_s
        self.attrs = attrs or {}
        self.proc = proc
        self.thread = thread
        self._t0_mono: Optional[float] = None

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "ts": self.ts, "duration_s": self.duration_s,
                "proc": self.proc, "thread": self.thread,
                "attrs": self.attrs}

    @classmethod
    def from_dict(cls, d: Dict) -> "Span":
        return cls(d["name"], d["trace_id"], d["span_id"],
                   d.get("parent_id"), d["ts"], d["duration_s"],
                   attrs=dict(d.get("attrs") or {}),
                   proc=d.get("proc", ""), thread=int(d.get("thread", 0)))

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}…, "
                f"span={self.span_id}, dur={self.duration_s * 1e3:.2f}ms)")


_tls = threading.local()


def current_context() -> Optional[SpanContext]:
    """The innermost span context pushed on THIS thread (``tracer().span``
    blocks and explicit :func:`use_context` handoffs push here).  The
    profiler's phase timers parent their child spans to it."""

    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def use_context(ctx: Optional[SpanContext]):
    """Adopt ``ctx`` as this thread's current span context (cross-thread
    handoff: the server's dispatcher/finalizer threads adopt a request's
    context around the device call so engine phase timers parent
    correctly).  ``None`` is a no-op."""

    if ctx is None:
        yield
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)
    try:
        yield
    finally:
        stack.pop()


def _truthy_env(name: str) -> bool:
    return os.environ.get(name, "0").strip().lower() not in (
        "", "0", "false", "no")


class Tracer:
    """Bounded span collector.

    Parameters
    ----------
    capacity
        Ring-buffer bound; the oldest spans fall off (``dropped_total``
        counts them) so an always-on tracer cannot grow a serving
        process without bound.
    enabled
        ``None`` reads ``DKS_TRACE``.
    proc
        Process label stamped on every span (``DKS_TRACE_PROC`` or
        ``pid<N>``); the chaos bench sets it per replica so merged
        traces keep their tracks apart.
    sink_dir
        ``None`` reads ``DKS_TRACE_DIR``.  When set, every finished span
        is appended (flushed) to ``<dir>/spans-<pid>.jsonl`` so a
        SIGKILLed worker loses at most the span in flight.
    sink_max_bytes, sink_max_age_s
        Sink rotation bounds (``DKS_TRACE_MAX_BYTES`` — default 64 MiB —
        and ``DKS_TRACE_MAX_AGE_S`` — default off).  A long-lived
        replica's sink file used to grow without limit; when either
        bound trips, the current file rotates to
        ``spans-<pid>.jsonl.1`` (ONE kept generation — the previous
        ``.1``'s spans are deleted and counted in
        :attr:`sink_dropped_total`) and a fresh file opens.  The
        per-span flush is unchanged, so the SIGKILL-safety contract
        holds across rotations.  ``0`` disables the respective bound.
    """

    def __init__(self, capacity: int = 8192,
                 enabled: Optional[bool] = None,
                 proc: Optional[str] = None,
                 sink_dir: Optional[str] = None,
                 sink_max_bytes: Optional[int] = None,
                 sink_max_age_s: Optional[float] = None):
        if enabled is None:
            enabled = _truthy_env("DKS_TRACE")
        self.enabled = bool(enabled)
        replica = os.environ.get("DKS_REPLICA_INDEX")
        self.proc = (proc or os.environ.get("DKS_TRACE_PROC")
                     or (f"replica{replica}" if replica is not None else None)
                     or f"pid{os.getpid()}")
        self.capacity = int(capacity)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.recorded_total = 0
        self._sink_dir = (sink_dir if sink_dir is not None
                          else os.environ.get("DKS_TRACE_DIR") or None)
        self._sink_fh = None
        self._sink_broken = False
        if sink_max_bytes is None:
            sink_max_bytes = int(os.environ.get("DKS_TRACE_MAX_BYTES",
                                                64 << 20) or 0)
        if sink_max_age_s is None:
            sink_max_age_s = float(os.environ.get("DKS_TRACE_MAX_AGE_S",
                                                  0) or 0)
        self.sink_max_bytes = max(0, int(sink_max_bytes))
        self.sink_max_age_s = max(0.0, float(sink_max_age_s))
        self._sink_bytes = 0
        self._sink_spans = 0
        self._sink_opened_mono = 0.0
        # spans living in the kept ``.1`` generation: deleted (and folded
        # into sink_dropped_total) when the NEXT rotation displaces it
        self._rotated_spans = 0
        self.sink_rotations_total = 0
        #: spans this process wrote to the sink and later deleted by
        #: rotation (the ``dks_trace_dropped_total`` source) — in-memory
        #: like ``recorded_total``; other processes' files are untouched
        self.sink_dropped_total = 0

    # ------------------------------------------------------------------ #

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def _sink_path(self) -> str:
        return os.path.join(self._sink_dir, f"spans-{os.getpid()}.jsonl")

    def _maybe_rotate_sink(self) -> None:
        """Rotate the sink file when a size/age bound trips (caller holds
        the lock and owns an open sink).  ONE generation is kept: the
        current file becomes ``.1``; the displaced ``.1``'s spans are
        deleted and counted as dropped."""

        over_bytes = (self.sink_max_bytes
                      and self._sink_bytes >= self.sink_max_bytes)
        over_age = (self.sink_max_age_s
                    and time.monotonic() - self._sink_opened_mono
                    >= self.sink_max_age_s)
        if not (over_bytes or over_age):
            return
        path = self._sink_path()
        self._sink_fh.close()
        self._sink_fh = None
        # the displaced kept generation is gone for good — its spans are
        # the ones this rotation actually drops (os.replace overwrites)
        if os.path.exists(path + ".1"):
            self.sink_dropped_total += self._rotated_spans
        os.replace(path, path + ".1")
        self._rotated_spans = self._sink_spans
        self._sink_bytes = 0
        self._sink_spans = 0
        self.sink_rotations_total += 1

    def _append(self, span: Span) -> None:
        with self._lock:
            self._buf.append(span)
            self.recorded_total += 1
            if self._sink_dir is not None and not self._sink_broken:
                try:
                    if self._sink_fh is None:
                        os.makedirs(self._sink_dir, exist_ok=True)
                        self._sink_fh = open(self._sink_path(), "a",
                                             encoding="utf-8")
                        self._sink_bytes = self._sink_fh.tell()
                        self._sink_opened_mono = time.monotonic()
                    line = json.dumps(span.to_dict()) + "\n"
                    self._sink_fh.write(line)
                    self._sink_fh.flush()
                    self._sink_bytes += len(line)
                    self._sink_spans += 1
                    self._maybe_rotate_sink()
                except OSError:
                    # a full/unwritable disk must not take serving down
                    self._sink_broken = True
                    logger.exception("span sink failed; disabling it")

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return max(0, self.recorded_total - len(self._buf))

    # ------------------------------------------------------------------ #

    def begin(self, name: str,
              parent: Union[SpanContext, Span, None] = None,
              **attrs) -> Span:
        """Start a span now; finish it with :meth:`end` (possibly from
        another call path on the same thread).  ``parent=None`` adopts
        the thread's current context, else mints a new trace."""

        if isinstance(parent, Span):
            parent = parent.context
        if parent is None:
            parent = current_context()
        trace_id = parent.trace_id if parent else new_trace_id()
        span = Span(name, trace_id, new_span_id(),
                    parent.span_id if parent else None,
                    mono_to_epoch(time.monotonic()), 0.0, attrs=attrs,
                    proc=self.proc, thread=threading.get_ident())
        span._t0_mono = time.monotonic()
        return span

    def end(self, span: Optional[Span], **attrs) -> None:
        if span is None:
            return
        t0 = span._t0_mono if span._t0_mono is not None else None
        span.duration_s = (time.monotonic() - t0) if t0 is not None else 0.0
        if attrs:
            span.attrs.update(attrs)
        self._append(span)

    @contextlib.contextmanager
    def span(self, name: str,
             parent: Union[SpanContext, Span, None] = None, **attrs):
        """Span as a context manager; pushes its context as the thread's
        current one so nested spans (and profiler phases) parent to it."""

        if not self.enabled:
            yield None
            return
        span = self.begin(name, parent=parent, **attrs)
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(span.context)
        try:
            yield span
        finally:
            stack.pop()
            self.end(span)

    def record_mono(self, name: str, t0_mono: float, t1_mono: float,
                    parent: Union[SpanContext, Span, None] = None,
                    trace_id: Optional[str] = None,
                    **attrs) -> Optional[SpanContext]:
        """Record an already-measured interval (monotonic endpoints) as a
        finished span — the cross-thread path: the dispatcher knows a
        request's enqueue and claim times, neither measured on the
        recording thread."""

        if not self.enabled:
            return None
        if isinstance(parent, Span):
            parent = parent.context
        if trace_id is None:
            trace_id = (parent.trace_id if parent else new_trace_id())
        span = Span(name, trace_id, new_span_id(),
                    parent.span_id if parent else None,
                    mono_to_epoch(t0_mono), max(0.0, t1_mono - t0_mono),
                    attrs=attrs, proc=self.proc,
                    thread=threading.get_ident())
        self._append(span)
        return span.context

    # ------------------------------------------------------------------ #

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.recorded_total = 0

    def export_jsonl(self, path: str) -> int:
        """Write the ring's spans as JSON lines; returns the count."""

        spans = self.spans()
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            for span in spans:
                fh.write(json.dumps(span.to_dict()) + "\n")
        return len(spans)


def read_jsonl(path: str) -> List[Span]:
    spans = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                spans.append(Span.from_dict(json.loads(line)))
    return spans


# --------------------------------------------------------------------- #
# Chrome / Perfetto trace_event conversion
# --------------------------------------------------------------------- #


def chrome_trace(spans: List[Span]) -> Dict:
    """Convert spans to the Chrome ``trace_event`` JSON object format
    (loadable in Perfetto / chrome://tracing).  Spans become complete
    ('X') events; processes get metadata naming events.  All span
    identity (trace/span/parent ids, attrs) rides in ``args`` so
    :func:`from_chrome_trace` can round-trip losslessly."""

    procs: Dict[str, int] = {}
    events = []
    for span in spans:
        pid = procs.setdefault(span.proc or "proc", len(procs) + 1)
        events.append({
            "ph": "X",
            "name": span.name,
            "cat": "dks",
            "ts": round(span.ts * 1e6, 3),
            "dur": round(span.duration_s * 1e6, 3),
            "pid": pid,
            "tid": span.thread or 1,
            "args": {"trace_id": span.trace_id, "span_id": span.span_id,
                     "parent_id": span.parent_id, **span.attrs},
        })
    meta = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": name}} for name, pid in procs.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(spans: List[Span], path: str) -> int:
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    doc = chrome_trace(spans)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return sum(1 for e in doc["traceEvents"] if e["ph"] == "X")


def from_chrome_trace(doc: Dict) -> List[Span]:
    """Inverse of :func:`chrome_trace` (round-trip check in the tests and
    the bench's ``--trace-out`` converter)."""

    proc_names = {e["pid"]: e["args"]["name"]
                  for e in doc.get("traceEvents", [])
                  if e.get("ph") == "M" and e.get("name") == "process_name"}
    spans = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = dict(e.get("args") or {})
        spans.append(Span(
            e["name"], args.pop("trace_id"), args.pop("span_id"),
            args.pop("parent_id", None), e["ts"] / 1e6, e["dur"] / 1e6,
            attrs=args, proc=proc_names.get(e["pid"], str(e["pid"])),
            thread=int(e.get("tid", 0))))
    return spans


def read_chrome_trace(path: str) -> List[Span]:
    with open(path, "r", encoding="utf-8") as fh:
        return from_chrome_trace(json.load(fh))


def phase_breakdown(spans: List[Span]) -> Dict[str, Dict[str, float]]:
    """Aggregate spans by name: the per-phase breakdown the benchmarks
    print with ``--trace-out`` (count / total / mean / max seconds)."""

    out: Dict[str, Dict[str, float]] = {}
    for span in spans:
        st = out.setdefault(span.name, {"count": 0, "total_s": 0.0,
                                        "max_s": 0.0})
        st["count"] += 1
        st["total_s"] += span.duration_s
        st["max_s"] = max(st["max_s"], span.duration_s)
    for st in out.values():
        st["mean_s"] = st["total_s"] / st["count"]
        st["total_s"] = round(st["total_s"], 6)
        st["mean_s"] = round(st["mean_s"], 6)
        st["max_s"] = round(st["max_s"], 6)
    return out


_default = Tracer()


def tracer() -> Tracer:
    """The process-wide default tracer (every producer in the serving /
    pool stack records here)."""

    return _default
