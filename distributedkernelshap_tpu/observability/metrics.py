"""Central metrics registry: typed Counter/Gauge/Histogram with labels and
ONE Prometheus text renderer.

Before this module, three subsystems each hand-rolled their own metrics
dicts and exposition-format rendering — ``serving/server.py`` (a counters
dict, a shed-reason dict and a hand-unrolled latency histogram inside
``_render_metrics``), ``serving/replicas.py`` (the ``dks_fanin_*`` block)
and ``scheduling/scheduler.py`` (depths rendered by the server).  None of
the renderers was ever format-checked, the fan-in proxy's per-replica
error counters were bare ``int +=`` from hedge threads, and a new metric
meant hand-writing HELP/TYPE lines in the right spot of a 90-line
f-string block.  This registry is the single place a ``dks_*`` series can
come from:

* **registration** — ``registry.counter(name, help, labelnames)`` (and
  ``gauge``/``histogram``) declares the metric once, with its type and
  label schema; re-registering a name with a different shape raises.
* **atomic updates** — every metric guards its series map with its own
  lock, so ``inc()`` from hedge/handler/finalizer threads never loses an
  update (the regression the fan-in's bare ints had).
* **callbacks** — gauges (and counters whose truth lives elsewhere, e.g.
  the profiler's phase totals) may be backed by a ``set_function``
  callable sampled at render time, so scrape-time state (queue depths,
  replica liveness, cache occupancy) needs no write-path bookkeeping.
* **one renderer** — ``registry.render()`` emits the whole exposition
  page: HELP/TYPE per family, escaped label values, cumulative histogram
  buckets with ``+Inf``/``_sum``/``_count``.  ``validate_exposition``
  checks any page against the format rules (used by the compliance test
  and ``scripts/obs_check.py``).
* **self-description** — ``registry.describe()`` returns the catalog
  (name/type/labels/help) that ``make obs-check`` diffs against
  ``docs/OBSERVABILITY.md`` so metrics cannot drift undocumented.

Stdlib-only, like the serving stack it instruments.
"""

import logging
import math
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distributedkernelshap_tpu.analysis import lockwitness

logger = logging.getLogger(__name__)

#: default last-K exemplars kept per histogram bucket (bounded: the
#: exemplar store can never grow a serving process — K recent trace ids
#: per bucket per series, nothing more)
DEFAULT_EXEMPLAR_SLOTS = 4

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_value(value) -> str:
    """Render a sample value: integral values print without a decimal
    point (``dks_serve_requests_total 6``, matching the pre-registry
    renderers and the string assertions in the test suite), everything
    else as the float's shortest repr."""

    f = float(value)
    if math.isfinite(f) and f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labelnames: Sequence[str], labelvalues: Sequence[str],
               extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(labelnames, labelvalues)]
    if extra is not None:
        pairs.append(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{n}="{_escape_label_value(str(v))}"'
                     for n, v in pairs)
    return "{" + inner + "}"


class _Metric:
    """Shared machinery: a name, a label schema, a lock, a series map."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r} for {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lockwitness.make_lock("metrics.metric")
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fn: Optional[Callable] = None
        # cardinality declaration (the obs-check label-cardinality lint):
        # metrics carrying tenant-shaped labels (``model``) must either
        # declare a hard series cap (``bound_cardinality``) or a retire
        # hook (``MetricsRegistry.declare_retirement``) so deleted
        # tenants cannot grow the label space forever.  ``None`` = no
        # declaration (fails the lint for model-labeled metrics).
        self.cardinality: Optional[str] = None
        if not self.labelnames:
            # an unlabeled metric renders from birth (``..._total 0``) —
            # scrapers and the string assertions in the test suite expect
            # a series to exist before its first increment
            self._values[()] = 0.0

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def set_function(self, fn: Callable) -> "_Metric":
        """Back this metric with a render-time callback.  For unlabeled
        metrics ``fn()`` returns a number; for labeled ones a dict mapping
        label-value tuples (ordered like ``labelnames``) to numbers.
        Callback metrics are read-only through the registry."""

        self._fn = fn
        return self

    def _sampled(self) -> Dict[Tuple[str, ...], float]:
        if self._fn is None:
            with self._lock:
                return dict(self._values)
        try:
            out = self._fn()
        except Exception:
            logger.exception("metric callback for %s failed", self.name)
            return {}
        if isinstance(out, dict):
            return {((k,) if isinstance(k, str) else tuple(str(x) for x in k)):
                    float(v) for k, v in out.items()}
        return {(): float(out)}

    def value(self, **labels) -> float:
        """Current value of one series (0.0 if never touched)."""

        return self._sampled().get(self._key(labels), 0.0)

    def bound_cardinality(self, bound: int) -> "_Metric":
        """Declare a hard cap on this metric's distinct label values (the
        writer enforces it, typically with an ``_overflow`` bucket); the
        obs-check lint accepts either this or a retire hook for
        model-labeled metrics."""

        self.cardinality = f"capped({int(bound)})"
        return self

    def _match_positions(self, match: Dict[str, str]):
        """``[(index, value), ...]`` for label names present in this
        metric's schema, or ``None`` when any match key is unknown."""

        positions = []
        for ln, lv in match.items():
            if ln not in self.labelnames:
                return None
            positions.append((self.labelnames.index(ln), str(lv)))
        return positions

    def retire_labels(self, match: Dict[str, str]) -> int:
        """Drop every series whose label values match ``match`` (a subset
        of the label schema — ``{"model": "m1"}`` retires all of m1's
        series whatever the other labels).  Returns the count removed.
        Callback-backed metrics are a no-op (their truth lives elsewhere;
        the owner retires it at the source)."""

        if self._fn is not None or not match:
            return 0
        positions = self._match_positions(match)
        if positions is None:
            return 0
        with self._lock:
            doomed = [k for k in self._values
                      if all(k[i] == v for i, v in positions)]
            for k in doomed:
                del self._values[k]
        return len(doomed)

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, val in self._sampled().items():
            lines.append(f"{self.name}{_label_str(self.labelnames, key)} "
                         f"{format_value(val)}")
        return lines

    def describe(self) -> Dict[str, object]:
        return {"name": self.name, "type": self.kind,
                "labels": list(self.labelnames), "help": self.help,
                "cardinality": self.cardinality}

    def collect(self) -> Dict[str, object]:
        """Structured snapshot for programmatic consumers (the time-series
        sampler): ``{"name", "type", "labelnames", "series"}`` where
        ``series`` maps label-value tuples to the current value."""

        return {"name": self.name, "type": self.kind,
                "labelnames": self.labelnames, "series": self._sampled()}


class Counter(_Metric):
    """Monotone counter.  ``inc`` is atomic under the metric lock, so
    concurrent handler/hedge/finalizer threads can never lose an update
    (the regression the fan-in proxy's bare ``int +=`` replica counters
    had)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def seed(self, *labelvalue_tuples) -> "Counter":
        """Pre-create series at 0 so known label values render before
        their first increment (the pre-registry renderers listed every
        shed reason from the start)."""

        with self._lock:
            for values in labelvalue_tuples:
                if isinstance(values, str):
                    values = (values,)
                key = tuple(str(v) for v in values)
                if len(key) != len(self.labelnames):
                    raise ValueError(f"seed {values!r} does not match "
                                     f"labels {self.labelnames}")
                self._values.setdefault(key, 0.0)
        return self


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    """Cumulative histogram with fixed bounded buckets.  Renders
    ``<name>_bucket{le=...}`` (cumulative), ``+Inf``, ``_sum`` and
    ``_count`` — exactly the shape the server's hand-unrolled latency
    histogram produced, now format-checked."""

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: Sequence[float],
                 labelnames: Sequence[str] = (), exemplar_slots: int = 0):
        super().__init__(name, help, labelnames)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("histogram buckets must be sorted and non-empty")
        self.buckets = tuple(float(b) for b in buckets)
        # per-series state: ([per-bucket counts + +Inf slot], sum, count)
        self._series: Dict[Tuple[str, ...], List] = {}
        # trace exemplars: last-K per (series, bucket) — an SLO breach on
        # this histogram links straight to the trace ids that landed in
        # its slow buckets.  0 disables (no storage, no overhead beyond
        # one int compare per observe).  Not rendered into the text
        # exposition (format 0.0.4 has no exemplar syntax); exposed via
        # :meth:`exemplars` → ``/debugz`` and ``/fleetz``.
        self.exemplar_slots = int(exemplar_slots)
        self._exemplars: Dict[Tuple[Tuple[str, ...], int], deque] = {}
        if not self.labelnames:
            # like the scalar metrics: an unlabeled histogram renders its
            # (all-zero) buckets from birth
            self._series[()] = [[0] * (len(self.buckets) + 1), 0.0, 0]

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation; ``exemplar`` (a trace id) is kept in
        the observation's bucket when exemplar slots are enabled."""

        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = [[0] * (len(self.buckets) + 1),
                                             0.0, 0]
            counts, _, _ = state
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    slot = i
                    break
            else:
                counts[-1] += 1
                slot = len(self.buckets)
            state[1] += value
            state[2] += 1
            if exemplar and self.exemplar_slots:
                ring = self._exemplars.get((key, slot))
                if ring is None:
                    ring = self._exemplars[(key, slot)] = deque(
                        maxlen=self.exemplar_slots)
                ring.append((str(exemplar), float(value), time.time()))

    def exemplars(self) -> List[Dict[str, object]]:
        """Bounded exemplar snapshot: one entry per stored exemplar —
        ``{"metric", "labels", "le", "trace_id", "value", "ts"}`` with
        ``le`` the observation's bucket upper bound (``"+Inf"`` for the
        overflow slot)."""

        with self._lock:
            snap = {k: list(v) for k, v in self._exemplars.items()}
        out = []
        for (key, slot), entries in snap.items():
            le = ("+Inf" if slot >= len(self.buckets)
                  else str(self.buckets[slot]))
            labels = dict(zip(self.labelnames, key))
            for trace_id, value, ts in entries:
                out.append({"metric": self.name, "labels": labels,
                            "le": le, "trace_id": trace_id,
                            "value": value, "ts": ts})
        return out

    def retire_labels(self, match: Dict[str, str]) -> int:
        if self._fn is not None or not match:
            return 0
        positions = self._match_positions(match)
        if positions is None:
            return 0
        with self._lock:
            doomed = [k for k in self._series
                      if all(k[i] == v for i, v in positions)]
            for k in doomed:
                del self._series[k]
            for ex_key in [ek for ek in self._exemplars
                           if ek[0] in set(doomed)]:
                del self._exemplars[ex_key]
        return len(doomed)

    def value(self, **labels):
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                return {"count": 0, "sum": 0.0}
            return {"count": state[2], "sum": state[1]}

    def collect(self) -> Dict[str, object]:
        """Histogram snapshot for the time-series sampler: each series is
        ``(per-bucket counts incl. the +Inf slot, sum, count)`` plus the
        shared bucket bounds."""

        with self._lock:
            series = {k: (tuple(v[0]), v[1], v[2])
                      for k, v in self._series.items()}
        return {"name": self.name, "type": self.kind,
                "labelnames": self.labelnames, "buckets": self.buckets,
                "series": series}

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            series = {k: ([list(v[0])], v[1], v[2])
                      for k, v in self._series.items()}
        for key, (counts_box, total, count) in series.items():
            counts = counts_box[0]
            cumulative = 0
            for bound, c in zip(self.buckets, counts):
                cumulative += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_label_str(self.labelnames, key, ('le', str(bound)))} "
                    f"{cumulative}")
            cumulative += counts[-1]
            lines.append(
                f"{self.name}_bucket"
                f"{_label_str(self.labelnames, key, ('le', '+Inf'))} "
                f"{cumulative}")
            lines.append(f"{self.name}_sum"
                         f"{_label_str(self.labelnames, key)} "
                         f"{format_value(total)}")
            lines.append(f"{self.name}_count"
                         f"{_label_str(self.labelnames, key)} {count}")
        return lines


class MetricsRegistry:
    """One component's metric namespace (the server and the proxy each own
    one — tests run several servers per process, so a global registry
    would collide).  Thread-safe; renders in registration order."""

    def __init__(self):
        self._lock = lockwitness.make_lock("metrics.registry")
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (type(existing) is not type(metric)
                        or existing.labelnames != metric.labelnames):
                    raise ValueError(
                        f"metric {metric.name} already registered with a "
                        f"different type or label set")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help: str,
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help, labelnames))

    def gauge(self, name: str, help: str,
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, labelnames))

    def histogram(self, name: str, help: str, buckets: Sequence[float],
                  labelnames: Sequence[str] = (),
                  exemplar_slots: int = 0) -> Histogram:
        return self._register(Histogram(name, help, buckets, labelnames,
                                        exemplar_slots=exemplar_slots))

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def declare_retirement(self, name: str) -> None:
        """Declare that some owner retires this metric's stale label
        values (``retire_labels`` on writes, or source-side removal for
        callback metrics) — the obs-check cardinality lint's alternative
        to a hard cap."""

        metric = self.get(name)
        if metric is None:
            raise ValueError(f"declare_retirement: unknown metric {name}")
        metric.cardinality = "retire-hook"

    def retire_labels(self, name: str, match: Dict[str, str]) -> int:
        """Drop every series of ``name`` whose labels match ``match``
        (subset match); returns the count removed, 0 for unknown metrics
        or label names — retiring is cleanup, never an error path."""

        metric = self.get(name)
        if metric is None:
            return 0
        return metric.retire_labels(match)

    def exemplars(self) -> List[Dict[str, object]]:
        """Every histogram's stored trace exemplars (bounded: last-K per
        bucket per series) — the ``/debugz`` exemplar payload."""

        with self._lock:
            metrics = list(self._metrics.values())
        out: List[Dict[str, object]] = []
        for m in metrics:
            if isinstance(m, Histogram):
                out.extend(m.exemplars())
        return out

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def describe(self) -> List[Dict[str, object]]:
        with self._lock:
            return [m.describe() for m in self._metrics.values()]

    def collect(self) -> List[Dict[str, object]]:
        """Snapshot every metric's current series (see
        :meth:`_Metric.collect`) — the time-series sampler's read path."""

        with self._lock:
            metrics = list(self._metrics.values())
        return [m.collect() for m in metrics]


# --------------------------------------------------------------------- #
# exposition-format parsing + validation (compliance test, obs-check)
# --------------------------------------------------------------------- #

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$")
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"\s*'
    r"(?:,|$)")


def _unescape_label_value(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str):
    """Parse a Prometheus text-format page into
    ``{family: {"type", "help", "samples": [(name, labels, value)]}}``.
    Raises ``ValueError`` on lines that do not parse at all; semantic
    problems are :func:`validate_exposition`'s job."""

    families: Dict[str, Dict] = {}

    def family_for(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[:-len(suffix)]
                if families.get(base, {}).get("type") == "histogram":
                    return base
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {"type": None, "help": None,
                                       "samples": []})["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            families.setdefault(name, {"type": None, "help": None,
                                       "samples": []})["type"] = kind.strip()
            continue
        if line.startswith("#"):
            continue  # free-form comment
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_PAIR_RE.match(raw, pos)
                if lm is None:
                    raise ValueError(
                        f"line {lineno}: bad label syntax in {line!r}")
                labels[lm.group("name")] = _unescape_label_value(
                    lm.group("value"))
                pos = lm.end()
        try:
            value = float(m.group("value").replace("+Inf", "inf"))
        except ValueError:
            raise ValueError(f"line {lineno}: bad value in {line!r}")
        fam = family_for(m.group("name"))
        families.setdefault(fam, {"type": None, "help": None,
                                  "samples": []})
        families[fam]["samples"].append((m.group("name"), labels, value))
    return families


def validate_exposition(text: str) -> List[str]:
    """Check a metrics page against the exposition-format rules the
    hand-rolled renderers were never tested for.  Returns a list of
    problems (empty = compliant)."""

    problems: List[str] = []
    if text and not text.endswith("\n"):
        problems.append("page does not end with a newline")
    try:
        families = parse_exposition(text)
    except ValueError as e:
        return problems + [str(e)]
    seen_samples = set()
    for fam, info in families.items():
        if not info["samples"]:
            continue
        if info["type"] is None:
            problems.append(f"{fam}: samples without a # TYPE line")
        if info["help"] is None:
            problems.append(f"{fam}: samples without a # HELP line")
        for name, labels, _ in info["samples"]:
            key = (name, tuple(sorted(labels.items())))
            if key in seen_samples:
                problems.append(f"{name}{labels}: duplicate sample")
            seen_samples.add(key)
            for ln in labels:
                if not _LABEL_NAME_RE.match(ln):
                    problems.append(f"{name}: invalid label name {ln!r}")
        if info["type"] == "histogram":
            problems.extend(_validate_histogram(fam, info["samples"]))
        if info["type"] == "counter":
            for name, labels, value in info["samples"]:
                if value < 0:
                    problems.append(f"{name}{labels}: negative counter")
    return problems


def _validate_histogram(fam: str, samples) -> List[str]:
    problems: List[str] = []
    # group by base labels (minus le)
    series: Dict[Tuple, Dict] = {}
    for name, labels, value in samples:
        base = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        st = series.setdefault(base, {"buckets": [], "sum": None,
                                      "count": None})
        if name == fam + "_bucket":
            if "le" not in labels:
                problems.append(f"{fam}_bucket missing le label")
                continue
            le = labels["le"]
            st["buckets"].append((math.inf if le == "+Inf" else float(le),
                                  value))
        elif name == fam + "_sum":
            st["sum"] = value
        elif name == fam + "_count":
            st["count"] = value
    for base, st in series.items():
        buckets = sorted(st["buckets"])
        if not buckets:
            # a histogram series may legitimately have no observations yet
            continue
        if buckets[-1][0] != math.inf:
            problems.append(f"{fam}{dict(base)}: no +Inf bucket")
        last = -1.0
        for bound, cum in buckets:
            if cum < last:
                problems.append(
                    f"{fam}{dict(base)}: bucket counts not monotone at "
                    f"le={bound}")
            last = cum
        if st["count"] is None:
            problems.append(f"{fam}{dict(base)}: missing _count")
        elif buckets[-1][0] == math.inf and st["count"] != buckets[-1][1]:
            problems.append(
                f"{fam}{dict(base)}: _count != +Inf bucket "
                f"({st['count']} vs {buckets[-1][1]})")
        if st["sum"] is None:
            problems.append(f"{fam}{dict(base)}: missing _sum")
    return problems
