"""Flight recorder: the last N structured events, queryable at ``/debugz``
and dumped to disk on an injected crash.

Chaos-bench failures used to be log archaeology: a shed here, a hedge
there, a supervisor restart in a third process's stderr, with no shared
ordering.  The flight recorder is one bounded, thread-safe ring of the
*interesting* events — sheds, hedges, replica deaths/recoveries,
restarts, wedges, journal invalidations, deadline expiries, fault
injections — that

* the server and fan-in proxy expose at ``/debugz`` (JSON; bounded, so a
  scrape can never OOM a serving process), and
* the fault harness dumps to ``$DKS_FLIGHTREC_DIR/flightrec-crash-<pid>.json``
  just before an injected ``crash`` fault ``os._exit``\\ s, turning a
  chaos failure into one artifact instead of scattered logs.

Events are plain dicts ``{"ts": epoch_s, "seq": n, "kind": str, ...}``.
The recorder is process-wide (one per process, like the tracer): every
subsystem records into the same ordered ring, which is exactly what makes
the timeline useful.
"""

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_CAPACITY = 512


def _jsonable(value):
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    return repr(value)


class FlightRecorder:
    """Bounded ring of structured events (see module doc)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.recorded_total = 0

    def record(self, kind: str, **fields) -> Dict:
        """Append one event; cheap and never raises (fields that are not
        JSON-serialisable are repr'd)."""

        event = {"ts": time.time(), "kind": str(kind)}
        for k, v in fields.items():
            event[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            self.recorded_total += 1
        return event

    def snapshot(self, kind: Optional[str] = None) -> List[Dict]:
        with self._lock:
            events = list(self._events)
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        return events

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.recorded_total = 0
            self._seq = 0

    def to_payload(self) -> Dict:
        """The ``/debugz`` response body: the ring plus its own
        accounting, so a consumer can tell "quiet" from "wrapped"."""

        with self._lock:
            events = list(self._events)
            recorded = self.recorded_total
        return {"capacity": self.capacity,
                "recorded_total": recorded,
                "dropped_total": max(0, recorded - len(events)),
                "events": events}

    def dump(self, path: str) -> str:
        """Write the ring to ``path`` as JSON; returns the path."""

        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        payload = self.to_payload()
        payload["dumped_at"] = time.time()
        payload["pid"] = os.getpid()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
            fh.flush()
            os.fsync(fh.fileno())
        return path

    def dump_crash(self, reason: str = "") -> Optional[str]:
        """Best-effort dump for the fault harness's ``crash`` path: writes
        to ``$DKS_FLIGHTREC_DIR`` (no-op when unset) and NEVER raises —
        this runs microseconds before ``os._exit`` and must not turn an
        injected crash into a different failure."""

        directory = os.environ.get("DKS_FLIGHTREC_DIR", "").strip()
        if not directory:
            return None
        try:
            self.record("crash_dump", reason=reason)
            return self.dump(os.path.join(
                directory, f"flightrec-crash-{os.getpid()}.json"))
        except Exception:
            logger.exception("flight-recorder crash dump failed")
            return None


_default = FlightRecorder()


def flightrec() -> FlightRecorder:
    """The process-wide flight recorder."""

    return _default
