"""Alert rules engine: pending → firing → resolved over the time-series
store, with pluggable sinks.

The SLO layer (``observability/slo.py``) says *whether* a condition
holds at an instant; this module adds the temporal discipline that makes
that an alert instead of noise:

* **for duration** — a condition must hold continuously for ``for_s``
  before the alert fires (a ``pending`` state in between, like
  Prometheus's ``for:``), so one bad scrape cannot page;
* **keep-firing duration** — once firing, the alert stays firing until
  the condition has been false for ``keep_firing_s``, so a flapping
  condition produces one alert, not a storm;
* **dedup** — one alert instance per rule; a rule that keeps evaluating
  true while firing notifies once (on the transition), not per tick;
* **silences** — ``silence(pattern, duration)`` suppresses sink
  notifications for matching rules (evaluation continues, so state is
  correct the moment the silence lapses).

Transitions are delivered to **sinks**: :class:`LogSink` (stderr via
logging), :class:`FlightRecorderSink` (the ``/debugz`` timeline — an
alert firing lands in the same ordered ring as the sheds/wedges that
caused it), :class:`WebhookSink` (JSON POST, fire-and-forget), and a
``dks_alerts_firing{rule=...}`` gauge the manager registers back into
the component's metrics registry so scrapers see alert state without a
second protocol.  Sinks must never raise into the evaluator; failures
are logged and dropped.

Stdlib-only; evaluation takes an explicit ``now`` so replays
(``scripts/health_check.py``) and tests are deterministic.
"""

import fnmatch
import json
import logging
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"  # transition event only; steady state is inactive


class AlertRule:
    """One named condition with its temporal thresholds.

    ``condition(store, now)`` returns truthiness, or a ``(bool, info)``
    pair whose ``info`` dict rides along on every transition event (the
    SLO rules put burn rates there).
    """

    def __init__(self, name: str, condition: Callable,
                 for_s: float = 0.0, keep_firing_s: float = 0.0,
                 severity: str = "page",
                 annotations: Optional[Dict[str, str]] = None):
        self.name = name
        self.condition = condition
        self.for_s = max(0.0, float(for_s))
        self.keep_firing_s = max(0.0, float(keep_firing_s))
        self.severity = severity
        self.annotations = dict(annotations or {})


def slo_burn_rule(slo, for_s: float = 30.0, keep_firing_s: float = 60.0,
                  severity: str = "page") -> AlertRule:
    """The standard rule over one SLO: condition = the SLO's own
    multi-window multi-burn-rate breach, info = its full status dict."""

    def condition(store, now):
        status = slo.evaluate(store, now=now)
        return status["breached"], {
            "slo": slo.name, "kind": slo.kind, "target": slo.target,
            "burn_rates": status["burn_rates"],
            "budget_remaining": status["budget_remaining"]}

    return AlertRule(f"slo_burn:{slo.name}", condition, for_s=for_s,
                     keep_firing_s=keep_firing_s, severity=severity,
                     annotations={"slo": slo.name,
                                  "description": slo.description})


class _AlertInstance:
    __slots__ = ("rule", "state", "pending_since", "firing_since",
                 "last_true", "last_info", "transitions_total",
                 "last_pending_notified")

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = INACTIVE
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.last_true: Optional[float] = None
        self.last_info: Dict = {}
        self.transitions_total = 0
        # last time a pending notification went out: a condition
        # flapping just under for_s must not spam sinks (and flood the
        # bounded flight-recorder ring) with one pending per blink
        self.last_pending_notified: Optional[float] = None


class Silence:
    __slots__ = ("pattern", "until")

    def __init__(self, pattern: str, until: float):
        self.pattern = pattern
        self.until = float(until)

    def matches(self, rule_name: str, now: float) -> bool:
        return now < self.until and fnmatch.fnmatch(rule_name, self.pattern)


# --------------------------------------------------------------------- #
# sinks
# --------------------------------------------------------------------- #


class LogSink:
    """Transitions to the process log (stderr under the default config)."""

    _LEVELS = {PENDING: logging.WARNING, FIRING: logging.ERROR,
               RESOLVED: logging.WARNING}

    def notify(self, event: Dict) -> None:
        logger.log(self._LEVELS.get(event["state"], logging.INFO),
                   "alert %s: %s (severity=%s) %s", event["state"],
                   event["rule"], event["severity"],
                   json.dumps(event.get("info", {}), default=repr))


class FlightRecorderSink:
    """Transitions onto the ``/debugz`` timeline, interleaved with the
    sheds/hedges/wedges that explain them."""

    def __init__(self, flight=None):
        if flight is None:
            from distributedkernelshap_tpu.observability.flightrec import (
                flightrec,
            )

            flight = flightrec()
        self.flight = flight

    def notify(self, event: Dict) -> None:
        self.flight.record("alert", rule=event["rule"],
                           state=event["state"],
                           severity=event["severity"],
                           component=event.get("component", ""),
                           info=event.get("info", {}))


class WebhookSink:
    """Fire-and-forget JSON POST per transition.  The POST runs on a
    short-lived daemon thread: the evaluator shares its thread with the
    registry sampler, and a slow/unreachable receiver blocking it for
    ``timeout_s`` would punch sample gaps into every windowed query
    exactly when an incident is producing transitions.  Failures are
    logged and dropped; ``wait()`` drains in-flight posts (tests)."""

    def __init__(self, url: str, timeout_s: float = 5.0):
        self.url = url
        self.timeout_s = float(timeout_s)
        self._inflight: List[threading.Thread] = []

    def _post(self, event: Dict) -> None:
        body = json.dumps(event, default=repr).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=self.timeout_s).close()
        except Exception as e:
            logger.warning("alert webhook %s failed: %s", self.url, e)

    def notify(self, event: Dict) -> None:
        t = threading.Thread(target=self._post, args=(event,),
                             daemon=True, name="dks-alert-webhook")
        self._inflight = [x for x in self._inflight if x.is_alive()]
        self._inflight.append(t)
        t.start()

    def wait(self, timeout_s: Optional[float] = None) -> None:
        for t in list(self._inflight):
            t.join(timeout=timeout_s if timeout_s is not None
                   else self.timeout_s + 1.0)


class CollectSink:
    """Append transitions to a list — replays and tests read it back."""

    def __init__(self):
        self.events: List[Dict] = []

    def notify(self, event: Dict) -> None:
        self.events.append(event)


# --------------------------------------------------------------------- #


class AlertManager:
    """Evaluate rules against the store, run the state machine, notify
    sinks on transitions (see module doc)."""

    def __init__(self, store, rules: Sequence[AlertRule],
                 sinks: Sequence = (), component: str = "",
                 pending_renotify_s: float = 60.0):
        self.store = store
        self.component = component
        self.sinks = list(sinks)
        #: minimum gap between two *pending* notifications of one rule
        #: (firing/resolved always notify — they are per-episode already)
        self.pending_renotify_s = float(pending_renotify_s)
        self._lock = threading.Lock()
        self._alerts: Dict[str, _AlertInstance] = {}
        self._silences: List[Silence] = []
        for rule in rules:
            self.add_rule(rule)

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            if rule.name in self._alerts:
                raise ValueError(f"duplicate alert rule {rule.name!r}")
            self._alerts[rule.name] = _AlertInstance(rule)

    def set_rules(self, rules: Sequence[AlertRule]) -> None:
        """Replace the rule set wholesale (the health engine's dynamic
        SLO refresh: tenant SLOs come and go with registry hot-swaps).
        Rules whose NAME survives keep their alert-instance state — a
        firing alert must not silently reset to inactive because an
        unrelated tenant registered; removed rules drop with their
        state."""

        rules = list(rules)
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            # validated BEFORE any mutation: a bad rule list must leave
            # the current set (and its alert state) fully untouched
            dup = next(n for n in names if names.count(n) > 1)
            raise ValueError(f"duplicate alert rule {dup!r}")
        with self._lock:
            replacement: Dict[str, _AlertInstance] = {}
            for rule in rules:
                inst = self._alerts.get(rule.name)
                if inst is not None:
                    inst.rule = rule
                    replacement[rule.name] = inst
                else:
                    replacement[rule.name] = _AlertInstance(rule)
            self._alerts = replacement

    def silence(self, pattern: str, duration_s: float,
                now: Optional[float] = None) -> Silence:
        """Suppress sink notifications for rules matching ``pattern``
        (fnmatch glob) for ``duration_s``.  Evaluation continues."""

        now = time.time() if now is None else now
        s = Silence(pattern, now + duration_s)
        with self._lock:
            self._silences.append(s)
        return s

    def _silenced(self, rule_name: str, now: float) -> bool:
        with self._lock:
            self._silences = [s for s in self._silences if now < s.until]
            return any(s.matches(rule_name, now) for s in self._silences)

    # -- evaluation ------------------------------------------------------ #

    def _make_event(self, alert: _AlertInstance, state: str,
                    now: float) -> Dict:
        """Build one notification event.  Caller holds ``self._lock`` so
        the event is consistent with the state it announces
        (``transitions_total`` moves with every STATE change, including
        dampened pending episodes that never notify)."""

        return {"ts": now, "rule": alert.rule.name, "state": state,
                "severity": alert.rule.severity,
                "component": self.component,
                "annotations": alert.rule.annotations,
                "info": alert.last_info}

    def _dispatch(self, event: Dict) -> None:
        if self._silenced(event["rule"], event["ts"]):
            event["silenced"] = True
            return
        for sink in self.sinks:
            try:
                sink.notify(event)
            except Exception:
                logger.exception("alert sink %r failed",
                                 type(sink).__name__)

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """One evaluation tick; returns the transition events it caused
        (empty on a steady-state tick).

        Conditions run OUTSIDE the lock (they scan the store and may be
        slow); each alert's state transition is applied UNDER the lock
        so concurrent ``payload()``/``firing_series()`` readers never
        observe a half-applied transition (e.g. ``firing`` with no
        ``firing_since``); sink notification happens after release (a
        sink may itself read manager state)."""

        now = time.time() if now is None else now
        with self._lock:
            alerts = list(self._alerts.values())
        events: List[Dict] = []
        for alert in alerts:
            rule = alert.rule
            try:
                verdict = rule.condition(self.store, now)
            except Exception:
                logger.exception("alert condition %s failed", rule.name)
                continue
            if isinstance(verdict, tuple):
                active, info = bool(verdict[0]), dict(verdict[1] or {})
            else:
                active, info = bool(verdict), {}
            event: Optional[Dict] = None
            with self._lock:
                if info:
                    alert.last_info = info
                if active:
                    alert.last_true = now
                    if alert.state == INACTIVE:
                        alert.pending_since = now
                        if rule.for_s > 0:
                            alert.state = PENDING
                            alert.transitions_total += 1
                            # dampen flapping: a fresh pending EPISODE
                            # only notifies if the last pending
                            # notification is old enough (the state
                            # machine always moves)
                            if (alert.last_pending_notified is None
                                    or now - alert.last_pending_notified
                                    >= self.pending_renotify_s):
                                alert.last_pending_notified = now
                                event = self._make_event(alert, PENDING,
                                                         now)
                        else:
                            alert.state = FIRING
                            alert.firing_since = now
                            alert.transitions_total += 1
                            event = self._make_event(alert, FIRING, now)
                    elif alert.state == PENDING \
                            and now - alert.pending_since >= rule.for_s:
                        alert.state = FIRING
                        alert.firing_since = now
                        alert.transitions_total += 1
                        event = self._make_event(alert, FIRING, now)
                else:
                    if alert.state == PENDING:
                        # the condition blinked before for_s: back to
                        # quiet, no resolved event (nothing ever fired)
                        alert.state = INACTIVE
                        alert.pending_since = None
                        alert.transitions_total += 1
                    elif alert.state == FIRING and (
                            alert.last_true is None
                            or now - alert.last_true
                            >= rule.keep_firing_s):
                        alert.state = INACTIVE
                        alert.firing_since = None
                        alert.pending_since = None
                        alert.transitions_total += 1
                        event = self._make_event(alert, RESOLVED, now)
            if event is not None:
                self._dispatch(event)
                events.append(event)
        return events

    # -- views ----------------------------------------------------------- #

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {name: a.state for name, a in self._alerts.items()}

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(name for name, a in self._alerts.items()
                          if a.state == FIRING)

    def firing_series(self) -> Dict[tuple, float]:
        """The ``dks_alerts_firing{rule=}`` gauge callback: 1 for firing
        rules, 0 otherwise — every rule renders from birth."""

        with self._lock:
            return {(name,): (1.0 if a.state == FIRING else 0.0)
                    for name, a in self._alerts.items()}

    def attach_metrics(self, registry) -> None:
        registry.gauge(
            "dks_alerts_firing",
            "Whether the named alert rule is currently firing.",
            labelnames=("rule",)).set_function(self.firing_series)

    def payload(self, now: Optional[float] = None) -> Dict:
        """Alert state for ``/statusz``: ``{"alerts": [one entry per
        rule], "silences": [active silences]}``."""

        now = time.time() if now is None else now
        with self._lock:
            alerts = list(self._alerts.values())
            silences = [{"pattern": s.pattern,
                         "expires_in_s": round(s.until - now, 1)}
                        for s in self._silences if now < s.until]
        out = []
        for a in alerts:
            since = a.firing_since if a.state == FIRING else a.pending_since
            out.append({
                "rule": a.rule.name, "state": a.state,
                "severity": a.rule.severity,
                "since_s": (round(now - since, 1)
                            if since is not None else None),
                "transitions_total": a.transitions_total,
                "info": a.last_info,
            })
        return {"alerts": sorted(out, key=lambda d: d["rule"]),
                "silences": silences}
