"""Bounded in-process time-series store + registry sampler.

The PR-3 registry answers "what is the value NOW"; nothing in the process
could answer "what was the error rate over the last minute" or "what was
interactive p99 in the last 30 s" — the inputs every SLO burn-rate
condition (``observability/slo.py``) and alert rule
(``observability/alerts.py``) needs.  This module closes that gap with a
deliberately small design:

* :class:`TimeSeriesStore` — one bounded ring (``deque(maxlen=...)``) per
  series.  A series is ``(metric name, label set)``; samples are
  ``(t, value)`` for counters/gauges and
  ``(t, cumulative bucket counts, sum, count)`` for histograms.  With the
  sampler's fixed interval, the ring is a fixed-width sliding window
  (default 600 samples x 1 s = 10 min of history) whose memory is bounded
  no matter how long the process lives.
* :class:`RegistrySampler` — a background thread that snapshots one or
  more live :class:`~distributedkernelshap_tpu.observability.metrics.
  MetricsRegistry` instances into the store every ``interval_s`` via
  ``registry.collect()`` (cheap: one dict copy per metric under its own
  lock — nothing on the request path).
* **query API** — :meth:`TimeSeriesStore.rate` (counter deltas/s, reset
  aware), :meth:`~TimeSeriesStore.avg_over` (gauge mean),
  :meth:`~TimeSeriesStore.quantile` (windowed histogram quantile with
  the standard Prometheus linear interpolation inside the bucket),
  :meth:`~TimeSeriesStore.delta` / :meth:`~TimeSeriesStore.histogram_window`
  (the windowed increments SLO math consumes), and
  :meth:`~TimeSeriesStore.points` for the ``/statusz`` sparklines.
* **JSONL export/replay** — :meth:`~TimeSeriesStore.export_jsonl` /
  :func:`load_jsonl`, so an incident's history can be pulled off a live
  process and replayed offline through the alert engine
  (``scripts/health_check.py`` replays a committed fixture as the CI
  golden test).

Stdlib-only like the rest of the package (the fan-in proxy imports this
before jax/numpy come up).  Timestamps are epoch seconds; every query
takes an explicit ``now`` so tests and replays are deterministic.
"""

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from distributedkernelshap_tpu.analysis import lockwitness

logger = logging.getLogger(__name__)

#: default samples kept per series — with the sampler's default 1 s
#: interval, ten minutes of history
DEFAULT_CAPACITY = 600

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def sparkline(values: Sequence[float]) -> str:
    """Unicode sparkline of ``values`` (empty string for no data)."""

    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_CHARS[0] * len(vals)
    span = hi - lo
    return "".join(
        SPARK_CHARS[min(len(SPARK_CHARS) - 1,
                        int((v - lo) / span * len(SPARK_CHARS)))]
        for v in vals)


class _Series:
    """One ring: scalar samples ``(t, value)`` or histogram samples
    ``(t, counts, sum, count)`` (cumulative, +Inf slot included)."""

    __slots__ = ("name", "labels", "kind", "buckets", "samples")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, capacity: int,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.buckets = buckets
        self.samples: deque = deque(maxlen=capacity)


class TimeSeriesStore:
    """Bounded per-series rings + the windowed query API (see module doc).

    Thread-safe: the sampler thread writes while ``/statusz`` handlers and
    the alert evaluator read.  All mutation happens under one lock; reads
    copy the (bounded) sample lists they need.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(2, int(capacity))
        self._series: Dict[tuple, _Series] = {}
        self._lock = lockwitness.make_lock("timeseries.store")
        self.samples_total = 0

    # -- write path ---------------------------------------------------- #

    def add(self, name: str, t: float, value: float,
            labels: Optional[Dict[str, str]] = None,
            kind: str = "gauge") -> None:
        """Append one scalar sample (``kind`` is ``counter`` or ``gauge``;
        it selects which queries make sense, not the storage)."""

        key = (name, _label_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _Series(
                    name, key[1], kind, self.capacity)
            series.samples.append((float(t), float(value)))
            self.samples_total += 1

    def add_histogram(self, name: str, t: float,
                      buckets: Sequence[float], counts: Sequence[int],
                      sum_value: float, count: int,
                      labels: Optional[Dict[str, str]] = None) -> None:
        """Append one cumulative histogram snapshot.  ``counts`` are the
        per-bucket counts INCLUDING the +Inf slot (i.e.
        ``len(counts) == len(buckets) + 1``), exactly what
        ``Histogram.collect()`` emits."""

        key = (name, _label_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _Series(
                    name, key[1], "histogram", self.capacity,
                    buckets=tuple(float(b) for b in buckets))
            series.samples.append((float(t), tuple(int(c) for c in counts),
                                   float(sum_value), int(count)))
            self.samples_total += 1

    # -- lookup -------------------------------------------------------- #

    def _get(self, name: str,
             labels: Optional[Dict[str, str]]) -> Optional[_Series]:
        with self._lock:
            return self._series.get((name, _label_key(labels)))

    def _snapshot(self, name: str, labels: Optional[Dict[str, str]]
                  ) -> Tuple[Optional[_Series], List[tuple]]:
        """Series + a consistent copy of its samples.  Every read path
        copies UNDER the lock: the sampler thread appends concurrently
        with /statusz handlers and scrape-time gauge callbacks, and
        iterating a deque mid-append raises."""

        with self._lock:
            series = self._series.get((name, _label_key(labels)))
            if series is None:
                return None, []
            return series, list(series.samples)

    @staticmethod
    def _in_window(samples: List[tuple], window_s: float,
                   now: float) -> List[tuple]:
        cutoff = now - window_s
        return [s for s in samples if cutoff <= s[0] <= now]

    def series_keys(self) -> List[Tuple[str, Dict[str, str]]]:
        with self._lock:
            return [(s.name, dict(s.labels)) for s in self._series.values()]

    def kind(self, name: str,
             labels: Optional[Dict[str, str]] = None) -> Optional[str]:
        series = self._get(name, labels)
        return series.kind if series is not None else None

    def labelsets(self, name: str) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(s.labels) for s in self._series.values()
                    if s.name == name]

    def latest(self, name: str,
               labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Most recent scalar value (None for missing/histogram series)."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram" or not samples:
            return None
        return samples[-1][1]

    def points(self, name: str, labels: Optional[Dict[str, str]] = None,
               window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Raw ``(t, value)`` scalar points (sparkline feed)."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram":
            return []
        if window_s is not None:
            now = time.time() if now is None else now
            samples = self._in_window(samples, window_s, now)
        return [(t, v) for t, v in samples]

    def rate_points(self, name: str,
                    labels: Optional[Dict[str, str]] = None,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None
                    ) -> List[Tuple[float, float]]:
        """Per-second increase between consecutive counter samples (reset
        clamps to 0) — the sparkline view of a cumulative counter."""

        pts = self.points(name, labels, window_s=window_s, now=now)
        out = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            dt = t1 - t0
            if dt > 0:
                out.append((t1, max(0.0, v1 - v0) / dt))
        return out

    # -- windowed queries (the SLO inputs) ----------------------------- #

    def delta(self, name: str, window_s: float,
              labels: Optional[Dict[str, str]] = None,
              now: Optional[float] = None) -> Optional[float]:
        """Total increase of a cumulative counter over the window (sum of
        positive steps, so a process-restart reset loses the pre-reset
        increment instead of going negative).  None = not enough samples
        in the window to say anything."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram":
            return None
        now = time.time() if now is None else now
        samples = self._in_window(samples, window_s, now)
        if len(samples) < 2:
            return None
        total = 0.0
        for (_, v0), (_, v1) in zip(samples, samples[1:]):
            if v1 > v0:
                total += v1 - v0
        return total

    def rate(self, name: str, window_s: float,
             labels: Optional[Dict[str, str]] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second rate of a counter over the window."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram":
            return None
        now = time.time() if now is None else now
        samples = self._in_window(samples, window_s, now)
        if len(samples) < 2:
            return None
        dt = samples[-1][0] - samples[0][0]
        if dt <= 0:
            return None
        # positive-step sum from the SAME snapshot (a second delta()
        # call would re-lock and could see a different sample set)
        increase = sum(v1 - v0 for (_, v0), (_, v1)
                       in zip(samples, samples[1:]) if v1 > v0)
        return increase / dt

    def avg_over(self, name: str, window_s: float,
                 labels: Optional[Dict[str, str]] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Mean of a gauge's samples over the window."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram":
            return None
        now = time.time() if now is None else now
        samples = self._in_window(samples, window_s, now)
        if not samples:
            return None
        return sum(v for _, v in samples) / len(samples)

    def frac_over(self, name: str, window_s: float, threshold: float,
                  labels: Optional[Dict[str, str]] = None,
                  now: Optional[float] = None) -> Optional[float]:
        """Fraction of the window's gauge samples strictly above
        ``threshold`` — the staleness-SLO primitive."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind == "histogram":
            return None
        now = time.time() if now is None else now
        samples = self._in_window(samples, window_s, now)
        if not samples:
            return None
        return sum(1 for _, v in samples if v > threshold) / len(samples)

    def histogram_window(self, name: str, window_s: float,
                         labels: Optional[Dict[str, str]] = None,
                         now: Optional[float] = None):
        """Windowed histogram increments: ``(bucket bounds, per-bucket
        count deltas incl. +Inf, sum delta, count delta)`` between the
        oldest and newest snapshot inside the window.  None without at
        least two snapshots (or on a reset, where deltas go negative)."""

        series, samples = self._snapshot(name, labels)
        if series is None or series.kind != "histogram":
            return None
        now = time.time() if now is None else now
        samples = self._in_window(samples, window_s, now)
        if len(samples) < 2:
            return None
        _, c0, s0, n0 = samples[0]
        _, c1, s1, n1 = samples[-1]
        if n1 < n0 or len(c0) != len(c1):
            return None  # reset mid-window: no honest delta exists
        counts = tuple(b - a for a, b in zip(c0, c1))
        if any(c < 0 for c in counts):
            return None
        return series.buckets, counts, s1 - s0, n1 - n0

    def quantile(self, name: str, q: float, window_s: float,
                 labels: Optional[Dict[str, str]] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Windowed ``q``-quantile (0..1) of a histogram, Prometheus
        ``histogram_quantile`` style: find the bucket the target rank
        lands in and interpolate linearly inside it.  None = no
        observations in the window."""

        win = self.histogram_window(name, window_s, labels, now=now)
        if win is None:
            return None
        bounds, counts, _, total = win
        if total <= 0:
            return None
        target = max(0.0, min(1.0, q)) * total
        cumulative = 0
        lower = 0.0
        for bound, c in zip(bounds, counts[:-1]):
            if cumulative + c >= target and c > 0:
                return lower + (bound - lower) * (target - cumulative) / c
            cumulative += c
            lower = bound
        # target lands in the +Inf bucket: the highest finite bound is the
        # most honest answer available
        return bounds[-1] if bounds else None

    def frac_le(self, name: str, threshold: float, window_s: float,
                labels: Optional[Dict[str, str]] = None,
                now: Optional[float] = None) -> Optional[float]:
        """Fraction of the window's histogram observations ``<=
        threshold``, interpolating when the threshold falls between
        bucket bounds — the latency-SLO primitive."""

        win = self.histogram_window(name, window_s, labels, now=now)
        if win is None:
            return None
        bounds, counts, _, total = win
        if total <= 0:
            return None
        cumulative = 0.0
        lower = 0.0
        for bound, c in zip(bounds, counts[:-1]):
            if threshold < bound:
                if c > 0 and bound > lower and threshold > lower:
                    cumulative += c * (threshold - lower) / (bound - lower)
                return max(0.0, min(1.0, cumulative / total))
            cumulative += c
            lower = bound
        return max(0.0, min(1.0, cumulative / total))

    # -- export / replay ----------------------------------------------- #

    def export_jsonl(self, path: str) -> int:
        """Append-free snapshot dump: every sample of every series as one
        JSON line, globally sorted by timestamp (so a replay evaluates in
        arrival order).  Returns the number of lines written."""

        with self._lock:
            series = list(self._series.values())
            rows = []
            for s in series:
                for sample in s.samples:
                    rows.append((sample[0], s, sample))
        rows.sort(key=lambda r: r[0])
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        n = 0
        with open(path, "w", encoding="utf-8") as fh:
            for t, s, sample in rows:
                doc = {"t": t, "name": s.name, "labels": dict(s.labels),
                       "kind": s.kind}
                if s.kind == "histogram":
                    _, counts, sum_value, count = sample
                    doc.update(buckets=list(s.buckets),
                               counts=list(counts),
                               sum=sum_value, count=count)
                else:
                    doc["value"] = sample[1]
                fh.write(json.dumps(doc) + "\n")
                n += 1
        return n

    def load_line(self, doc: Dict) -> None:
        """Ingest one exported line (see :meth:`export_jsonl`)."""

        if doc.get("kind") == "histogram":
            self.add_histogram(doc["name"], doc["t"], doc["buckets"],
                               doc["counts"], doc["sum"], doc["count"],
                               labels=doc.get("labels"))
        else:
            self.add(doc["name"], doc["t"], doc["value"],
                     labels=doc.get("labels"),
                     kind=doc.get("kind", "gauge"))


def load_jsonl(path: str,
               capacity: int = DEFAULT_CAPACITY * 10) -> TimeSeriesStore:
    """Replay an exported JSONL file into a fresh store (torn trailing
    lines — a dump cut off mid-write — are skipped with a warning, like
    the shard journal's torn-tail rule)."""

    store = TimeSeriesStore(capacity=capacity)
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                store.load_line(json.loads(line))
            except (ValueError, KeyError):
                logger.warning("%s:%d: skipping unparseable sample line",
                               path, lineno)
    return store


def iter_jsonl_times(store: TimeSeriesStore) -> List[float]:
    """Sorted unique sample timestamps — the evaluation points a replay
    steps through."""

    with store._lock:
        times = {s[0] for series in store._series.values()
                 for s in series.samples}
    return sorted(times)


class RegistrySampler:
    """Snapshot live registries into a :class:`TimeSeriesStore` on a fixed
    interval (see module doc).  ``sample_once`` is also public so tests
    and replays can drive deterministic ticks without a thread."""

    def __init__(self, store: TimeSeriesStore, registries: Iterable,
                 interval_s: float = 1.0):
        self.store = store
        self.registries = list(registries)
        self.interval_s = float(interval_s)
        self.samples_taken = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for registry in self.registries:
            try:
                collected = registry.collect()
            except Exception:
                logger.exception("registry collect failed")
                continue
            for metric in collected:
                names = metric["labelnames"]
                if metric["type"] == "histogram":
                    for key, (counts, sum_value, count) in \
                            metric["series"].items():
                        self.store.add_histogram(
                            metric["name"], now, metric["buckets"], counts,
                            sum_value, count,
                            labels=dict(zip(names, key)))
                else:
                    kind = ("counter" if metric["type"] == "counter"
                            else "gauge")
                    for key, value in metric["series"].items():
                        self.store.add(metric["name"], now, value,
                                       labels=dict(zip(names, key)),
                                       kind=kind)
        self.samples_taken += 1

    # -- background loop ----------------------------------------------- #

    def _loop(self, on_tick) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
                if on_tick is not None:
                    on_tick()
            except Exception:
                logger.exception("sampler tick failed")

    def start(self, on_tick=None) -> "RegistrySampler":
        """Start the background thread (``interval_s <= 0`` disables it —
        the store then only ever sees explicit ``sample_once`` calls).
        ``on_tick`` runs after each sample — the health engine hangs the
        alert evaluation off it so one thread drives both."""

        if self.interval_s <= 0 or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, args=(on_tick,),
                                        name="dks-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
