"""Per-tenant device-time metering: who consumed the fleet's
device-seconds, and how much of everything else.

Since cross-tenant continuous batching (PR 11) one padded device program
can carry rows from several tenants, and five evaluation paths share the
same dispatcher — so no pre-existing metric could answer "which
tenant/path consumed the device-seconds this hour".  The Gemma-on-TPU
continuous-batching analysis (PAPERS.md, arXiv 2605.25645) identifies
exactly this per-workload device-time accounting as what makes
shared-batch serving operable: without it, chargeback, capacity planning
and noisy-neighbour triage all read one aggregate number.

The :class:`CostMeter` brackets every dispatched device call at the
server's dispatch→fetch boundary (the donated ``jit_batch_entry``
dispatch through the blocking D2H fetch — fetch completion IS
block-until-ready), on the monotonic clock, with backend **compile time
excluded** via the process-global compile accountant
(``runtime/compile_cache.compile_events()``): a fresh bucket shape's
40 s trace+compile must not bill a tenant 40 s of device work.

**Proration rule** (shared cross-tenant batches): one device call's
seconds are split across the member tenants proportionally to their row
counts in the padded program — tenant *i* is charged
``rows_i / sum(rows)`` of the measured interval.  Bucket-padding rows
are charged pro-rata (the padding exists to serve the whole group;
per-tenant padding waste is separately visible as
``dks_serve_padded_rows_total``).  Shares sum to exactly 1, so summing
``dks_device_seconds_total`` over tenants recovers the directly
measured dispatch total — the invariant
``benchmarks/cost_attribution_bench.py --check`` enforces to 5 %.

**Bounded label cardinality**: tenant label values pass through a hard
cap (default 64 distinct ``model`` ids); the first request of tenant
65 is attributed to the explicit ``_overflow`` bucket instead of
minting a new label — a tenant flood can never blow up the registry.
Retired tenants release their slot (and their series) through
:meth:`retire_tenant`, called by ``ModelRegistry`` on hot-swap (old
version's series) and tenant removal (everything).

Stdlib-only at module scope, like the rest of ``observability/``; the
compile accountant is imported lazily on first use.
"""

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from distributedkernelshap_tpu.observability.metrics import (
    DEFAULT_EXEMPLAR_SLOTS,
)

logger = logging.getLogger(__name__)

#: the explicit overflow tenant label (cap exceeded — see module doc)
OVERFLOW_LABEL = "_overflow"

#: default cap on distinct tenant (``model``) label values per meter
DEFAULT_MAX_TENANTS = 64

#: per-tenant latency histogram bounds — the per-tenant latency SLOs
#: (``slo.tenant_slos``) burn against these, so every tenant SLO
#: threshold must stay at or below the largest finite bucket (the same
#: contract as the server's LATENCY_BUCKETS_S)
TENANT_LATENCY_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                            1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: every model-labeled family the meter owns — the retire hook drops a
#: removed tenant's series from each of these
TENANT_METRICS = (
    "dks_device_seconds_total",
    "dks_tenant_rows_total",
    "dks_tenant_wire_bytes_total",
    "dks_tenant_requests_total",
    "dks_tenant_errors_total",
    "dks_tenant_cache_hits_total",
    "dks_tenant_sheds_total",
    "dks_tenant_latency_seconds",
)


class CostMeter:
    """One serving component's tenant cost-attribution plane (the server
    owns one next to its ``MetricsRegistry``; see module doc).

    ``enabled=False`` keeps every record method a cheap early return —
    the metric families still register (the catalog is mode-independent)
    but nothing on the request path pays for bookkeeping.
    """

    def __init__(self, enabled: bool = True,
                 max_tenants: int = DEFAULT_MAX_TENANTS):
        self.enabled = bool(enabled)
        self.max_tenants = int(max_tenants)
        #: device-seconds scale factor: 1.0 on single-process servers; a
        #: multi-host pod lead sets it to the process count
        #: (serving/multihost.serve_multihost) because one SPMD dispatch
        #: occupies EVERY process's devices for the lead-measured
        #: interval — billing only the lead's share under-charges an
        #: N-host pod N-fold
        self.device_multiplier = 1.0
        self._lock = threading.Lock()
        self._tenants: set = set()
        self._overflowed_total = 0
        self._registry = None
        self._compile = None  # lazy: runtime/compile_cache.compile_events

    # -- registration --------------------------------------------------- #

    def attach_metrics(self, registry) -> None:
        """Register the ``dks_device_*`` / ``dks_tenant_*`` families on
        ``registry``.  All tenant-labeled families declare the hard cap
        (obs-check cardinality lint); single-model servers attribute to
        ``model="default"``."""

        self._registry = registry
        cap = self.max_tenants
        self._m_device_seconds = registry.counter(
            "dks_device_seconds_total",
            "Device-seconds consumed per (model, version, evaluation "
            "path), measured at the dispatch-to-fetch boundary on the "
            "monotonic clock with backend compile time excluded; shared "
            "cross-tenant batches are prorated by padded-row share, and "
            "multi-host pod leads scale by the pod's process count "
            "(docs/OBSERVABILITY.md, cost attribution).",
            labelnames=("model", "version", "path")).bound_cardinality(cap)
        self._m_rows = registry.counter(
            "dks_tenant_rows_total",
            "Instance rows answered per tenant (cache hits included — "
            "the served-rows twin of dks_serve_rows_total, by model).",
            labelnames=("model",)).bound_cardinality(cap)
        self._m_wire_bytes = registry.counter(
            "dks_tenant_wire_bytes_total",
            "Payload bytes on /explain per tenant and direction (rx = "
            "request bodies after routing, tx = success responses).",
            labelnames=("model", "direction")).bound_cardinality(cap)
        self._m_requests = registry.counter(
            "dks_tenant_requests_total",
            "Requests answered per tenant (the per-tenant availability "
            "SLO's total counter; errors included).",
            labelnames=("model",)).bound_cardinality(cap)
        self._m_errors = registry.counter(
            "dks_tenant_errors_total",
            "Requests answered with an error per tenant (the per-tenant "
            "availability SLO's bad counter).",
            labelnames=("model",)).bound_cardinality(cap)
        self._m_cache_hits = registry.counter(
            "dks_tenant_cache_hits_total",
            "Requests answered from the result cache per tenant (incl. "
            "in-batch dedup) — answered rows that cost no device time.",
            labelnames=("model",)).bound_cardinality(cap)
        self._m_sheds = registry.counter(
            "dks_tenant_sheds_total",
            "Requests shed before dispatch per tenant, by reason (every "
            "dks_serve_sheds_total reason, attributed to the routed "
            "tenant; single-model servers attribute to model=default).",
            labelnames=("model", "reason")).bound_cardinality(cap)
        self._m_latency = registry.histogram(
            "dks_tenant_latency_seconds",
            "Queue+explain latency of answered requests per tenant — "
            "the histogram per-tenant latency SLOs burn against; "
            "observations carry trace exemplars (/debugz).",
            buckets=TENANT_LATENCY_BUCKETS_S, labelnames=("model",),
            exemplar_slots=DEFAULT_EXEMPLAR_SLOTS).bound_cardinality(cap)
        registry.counter(
            "dks_tenant_label_overflow_total",
            "Attribution events folded into the _overflow tenant because "
            "the distinct-model label cap was reached (a tenant flood "
            "cannot grow the metric registry).").set_function(
            lambda: float(self._overflowed_total))

    # -- tenant label guard --------------------------------------------- #

    def label(self, model_id: Optional[str]) -> str:
        """The bounded metric label for one tenant id: known ids pass
        through, new ids claim a slot while the cap allows, everything
        past the cap lands in the explicit ``_overflow`` bucket."""

        mid = "default" if not model_id else str(model_id)
        with self._lock:
            if mid in self._tenants:
                return mid
            if len(self._tenants) < self.max_tenants:
                self._tenants.add(mid)
                return mid
            self._overflowed_total += 1
        return OVERFLOW_LABEL

    def retire_tenant(self, model_id: str,
                      version: Optional[int] = None) -> int:
        """Retire one tenant's stale label values.  With ``version``
        given (a hot-swap), only the version-labeled device-seconds
        series of that version are dropped — the tenant keeps its slot
        and its version-free tallies.  Without it (tenant removal),
        every family sheds the tenant's series and its cap slot frees.
        Returns the series count removed."""

        if self._registry is None:
            return 0
        removed = 0
        if version is not None:
            return self._registry.retire_labels(
                "dks_device_seconds_total",
                {"model": str(model_id), "version": str(version)})
        for name in TENANT_METRICS:
            removed += self._registry.retire_labels(
                name, {"model": str(model_id)})
        with self._lock:
            self._tenants.discard(str(model_id))
        return removed

    # -- device-time metering ------------------------------------------- #

    def set_device_multiplier(self, n_processes) -> None:
        """Scale every settled dispatch bracket by ``n_processes`` (see
        ``device_multiplier``); clamped to >= 1."""

        self.device_multiplier = max(1.0, float(n_processes))

    def _compile_seconds(self) -> float:
        if self._compile is None:
            from distributedkernelshap_tpu.runtime.compile_cache import (
                compile_events,
            )

            self._compile = compile_events()
        return self._compile.total_seconds()

    def begin(self) -> Optional[Tuple[float, float]]:
        """Open one dispatch bracket: ``(t_mono, compile_seconds)``
        snapshots, taken on the dispatcher thread just before the device
        call.  ``None`` when metering is off (settle then no-ops)."""

        if not self.enabled:
            return None
        return (time.monotonic(), self._compile_seconds())

    def settle(self, tx: Optional[Tuple[float, float]],
               shares: Sequence[Tuple[Optional[str], object, Optional[str],
                                      int]],
               t_end: Optional[float] = None,
               compile_end: Optional[float] = None) -> float:
        """Close a dispatch bracket and attribute its device-seconds.

        ``shares`` is ``[(model_id, version, path, rows), ...]`` — one
        entry per tenant in the dispatched group (``model_id=None`` for
        single-model servers).  The measured interval, minus the compile
        seconds that accrued inside it, is split by row share (see the
        module-doc proration rule).  ``t_end``/``compile_end`` default
        to "now" (tests pass explicit values for determinism).  Returns
        the device-seconds attributed."""

        if tx is None or not self.enabled or not shares:
            return 0.0
        t0, c0 = tx
        if t_end is None:
            t_end = time.monotonic()
        if compile_end is None:
            compile_end = self._compile_seconds()
        elapsed = max(0.0, (t_end - t0) - max(0.0, compile_end - c0)) \
            * self.device_multiplier
        total_rows = sum(max(0, int(r)) for _, _, _, r in shares)
        if total_rows <= 0:
            return 0.0
        for model_id, version, path, rows in shares:
            rows = max(0, int(rows))
            if not rows:
                continue
            self._m_device_seconds.inc(
                elapsed * (rows / total_rows),
                model=self.label(model_id),
                version=str(version if version is not None else 0),
                path=str(path) if path else "unknown")
        return elapsed

    # -- per-request accounting ----------------------------------------- #

    def record_answer(self, model_id: Optional[str], rows: int,
                      elapsed_s: float, error: bool, cache_hit: bool,
                      exemplar: Optional[str] = None) -> None:
        """One answered request's tenant accounting (requests, errors,
        rows, cache hits, latency + trace exemplar)."""

        if not self.enabled:
            return
        mid = self.label(model_id)
        self._m_requests.inc(model=mid)
        self._m_rows.inc(max(0, int(rows)), model=mid)
        if error:
            self._m_errors.inc(model=mid)
        elif cache_hit:
            self._m_cache_hits.inc(model=mid)
        self._m_latency.observe(float(elapsed_s), exemplar=exemplar,
                                model=mid)

    def record_shed(self, model_id: Optional[str], reason: str) -> None:
        if not self.enabled:
            return
        self._m_sheds.inc(model=self.label(model_id), reason=str(reason))

    def record_wire(self, model_id: Optional[str], direction: str,
                    nbytes: int) -> None:
        if not self.enabled or nbytes <= 0:
            return
        self._m_wire_bytes.inc(int(nbytes), model=self.label(model_id),
                               direction=str(direction))


def dispatch_shares(leaders, default_path: Optional[str] = None
                    ) -> List[Tuple[Optional[str], object,
                                    Optional[str], int]]:
    """Fold one dispatch group's live leaders into per-tenant
    ``(model_id, version, path, rows)`` shares (the ``split_sizes`` view
    of the batch, aggregated by pinned tenant version).  Leaders without
    a pinned registry model fold into the ``(None, 0, default_path)``
    default tenant (single-model servers)."""

    agg: "Dict[Tuple[Optional[str], object, Optional[str]], int]" = {}
    order: List[Tuple[Optional[str], object, Optional[str]]] = []
    for p in leaders:
        rm = getattr(p, "model", None)
        if rm is not None:
            model = rm.model
            key = (rm.model_id, rm.version,
                   getattr(model, "explain_path", None) if model is not None
                   else None)
        else:
            key = (None, 0, default_path)
        if key not in agg:
            agg[key] = 0
            order.append(key)
        agg[key] += int(getattr(p, "rows", 0))
    return [(mid, ver, path, agg[(mid, ver, path)])
            for mid, ver, path in order]
