"""Federated fleet telemetry: merge per-replica expositions into one
page and roll per-tenant cost up across the fleet.

Podracer's host/mesh split (PAPERS.md, arXiv 2104.06272) assumes exactly
the per-host telemetry rollup the ``FanInProxy`` lacked: every replica
exposes its own ``/metrics``, so answering "how many device-seconds did
tenant X consume across the fleet" meant N scrapes and hand-written
PromQL.  This module is the merge/rollup core behind the proxy's two new
read paths:

* ``GET /metrics?federate=1`` — every routable replica's exposition,
  merged into ONE compliant page with a ``replica`` label distinguishing
  the sources (:func:`merge_expositions`): HELP/TYPE rendered once per
  family, per-replica histogram series kept separately monotone, the
  whole page re-validating under ``validate_exposition``.
* ``GET /fleetz`` — the interpreted rollup (:func:`fleet_rollup`):
  per-tenant device-seconds / rows / requests / errors / sheds / wire
  bytes summed across replicas, per-tenant SLO budget remaining (the
  minimum across replicas — the fleet is only as healthy as its worst
  member), top-N tenants by cost, and the trace exemplars that link an
  SLO breach to concrete Perfetto-viewable traces.

**Conflicting TYPE lines**: two replicas disagreeing on a family's type
(a mid-rolling-upgrade fleet) cannot produce a valid merged family.  The
merge keeps the FIRST-seen replica's type and DROPS the conflicting
replicas' samples for that family (counted in the merge report) — a
deterministic rule that keeps the page valid instead of emitting a
family that fails bucket/type validation downstream.

Pure functions over parsed expositions — no sockets here; the proxy owns
the scraping (pooled connections, timeouts, error accounting).
"""

import time
from typing import Dict, List, Optional, Sequence, Tuple

from distributedkernelshap_tpu.observability.metrics import (
    _escape_help,
    _escape_label_value,
    format_value,
    parse_exposition,
)

#: the label the merge stamps on every federated sample; a replica-side
#: sample already carrying it is overwritten (the proxy's view of which
#: replica answered wins — it is the one that scraped)
REPLICA_LABEL = "replica"

#: tenants listed in the rollup's top-by-cost table
TOP_TENANTS = 10


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def merge_expositions(pages: Dict[str, str],
                      replica_label: str = REPLICA_LABEL
                      ) -> Tuple[str, Dict]:
    """Merge per-replica exposition pages (``{replica_value: page_text}``)
    into one compliant page with ``replica_label`` stamped on every
    sample.  Returns ``(merged_text, report)`` where ``report`` carries
    ``{"families": n, "samples": n, "replicas": [...],
    "type_conflicts": [(family, replica, type), ...],
    "parse_failures": [(replica, error), ...]}``.

    Merge rules (see module doc): HELP/TYPE once per family
    (first-seen replica wins — iteration follows ``pages`` order, which
    the proxy keeps sorted by replica index for determinism); samples of
    a replica whose TYPE conflicts with the established one are dropped
    and reported; histogram sample ordering within one (replica, series)
    preserves the source page's bucket order, so per-series bucket
    monotonicity survives the merge."""

    families: "Dict[str, Dict]" = {}
    order: List[str] = []
    report = {"families": 0, "samples": 0, "replicas": list(pages),
              "type_conflicts": [], "parse_failures": []}
    for replica, text in pages.items():
        try:
            parsed = parse_exposition(text)
        except ValueError as e:
            report["parse_failures"].append((replica, str(e)))
            continue
        for fam, info in parsed.items():
            if not info["samples"]:
                continue
            existing = families.get(fam)
            if existing is None:
                families[fam] = {"type": info["type"] or "untyped",
                                 "help": info["help"] or fam,
                                 "samples": []}
                order.append(fam)
            elif (info["type"] or "untyped") != existing["type"]:
                # conflicting TYPE (untyped counts as its own type —
                # merging an untyped replica's plain samples into a
                # histogram family, or histogram samples into an
                # untyped one, breaks sample grouping downstream):
                # this replica's samples for the family cannot merge
                # validly — drop them, loudly
                report["type_conflicts"].append(
                    (fam, replica, info["type"] or "untyped"))
                continue
            for name, labels, value in info["samples"]:
                merged = dict(labels)
                merged[replica_label] = str(replica)
                families[fam]["samples"].append((name, merged, value))
    lines: List[str] = []
    for fam in order:
        info = families[fam]
        lines.append(f"# HELP {fam} {_escape_help(info['help'])}")
        lines.append(f"# TYPE {fam} {info['type']}")
        for name, labels, value in info["samples"]:
            lines.append(f"{name}{_render_labels(labels)} "
                         f"{format_value(value)}")
        report["samples"] += len(info["samples"])
    report["families"] = len(order)
    return ("\n".join(lines) + "\n") if lines else "\n", report


# --------------------------------------------------------------------- #
# rollup
# --------------------------------------------------------------------- #

def _sum_counter(parsed: Dict, name: str, by_label: str = "model",
                 skip_labels: Sequence[str] = ()) -> Dict[str, float]:
    """Sum one family's samples by one label value (histograms excluded;
    use the ``_sum``/``_count`` derived names for those)."""

    out: Dict[str, float] = {}
    fam = parsed.get(name)
    if not fam:
        return out
    for sample_name, labels, value in fam["samples"]:
        if sample_name != name:
            continue  # histogram-derived samples handled by caller
        if any(labels.get(s) for s in skip_labels):
            continue
        key = labels.get(by_label)
        if key is None:
            continue
        out[key] = out.get(key, 0.0) + value
    return out


def _tenant_block(parsed: Dict) -> Dict[str, Dict]:
    """Per-tenant scalar sums from ONE replica's parsed exposition."""

    tenants: Dict[str, Dict] = {}

    def fold(field: str, values: Dict[str, float]) -> None:
        for model, v in values.items():
            tenants.setdefault(model, {})[field] = \
                tenants.get(model, {}).get(field, 0.0) + v

    device = {}
    fam = parsed.get("dks_device_seconds_total")
    if fam:
        for name, labels, value in fam["samples"]:
            model = labels.get("model")
            if model is None:
                continue
            device[model] = device.get(model, 0.0) + value
    fold("device_seconds", device)
    fold("rows", _sum_counter(parsed, "dks_tenant_rows_total"))
    fold("requests", _sum_counter(parsed, "dks_tenant_requests_total"))
    fold("errors", _sum_counter(parsed, "dks_tenant_errors_total"))
    fold("cache_hits", _sum_counter(parsed, "dks_tenant_cache_hits_total"))
    sheds = {}
    fam = parsed.get("dks_tenant_sheds_total")
    if fam:
        for name, labels, value in fam["samples"]:
            model = labels.get("model")
            if model is not None:
                sheds[model] = sheds.get(model, 0.0) + value
    fold("sheds", sheds)
    # device-memory ledger gauge (observability/memledger.py): computed
    # device bytes by owning model — the capacity twin of device_seconds
    # (gauge, so the fleet sum is a point-in-time footprint)
    mem = {}
    fam = parsed.get("dks_device_bytes")
    if fam:
        for name, labels, value in fam["samples"]:
            model = labels.get("model")
            if model is None:
                continue
            mem[model] = mem.get(model, 0.0) + value
    fold("device_bytes", mem)
    wire = parsed.get("dks_tenant_wire_bytes_total")
    if wire:
        for name, labels, value in wire["samples"]:
            model, direction = labels.get("model"), labels.get("direction")
            if model is None or direction not in ("rx", "tx"):
                continue
            field = f"wire_bytes_{direction}"
            tenants.setdefault(model, {})[field] = \
                tenants.get(model, {}).get(field, 0.0) + value
    return tenants


def _tenant_of_slo(slo_name: str) -> Optional[str]:
    """The model id behind a templated per-tenant SLO name
    (``tenant:<id>_latency`` / ``tenant:<id>_availability`` — see
    ``slo.tenant_slos``), or ``None`` for fleet-level SLOs."""

    if not slo_name.startswith("tenant:"):
        return None
    return slo_name[len("tenant:"):].rsplit("_", 1)[0]


def fleet_rollup(parsed_pages: Dict[str, Dict],
                 exemplars: Optional[Dict[str, List[Dict]]] = None,
                 replica_meta: Optional[Dict[str, Dict]] = None,
                 top_n: int = TOP_TENANTS,
                 now: Optional[float] = None) -> Dict:
    """The ``/fleetz`` document from per-replica parsed expositions
    (``{replica_value: parse_exposition(page)}``), optional per-replica
    exemplar lists (each entry as ``Histogram.exemplars`` yields them)
    and optional replica metadata (address, state).  Stable schema —
    documented in docs/OBSERVABILITY.md — consumed by operators, the
    autoscaler and the cost-attribution bench alike."""

    tenants: Dict[str, Dict] = {}
    budgets: Dict[str, float] = {}
    per_replica_device: Dict[str, Dict[str, float]] = {}
    slo_budgets: Dict[str, float] = {}
    for replica, parsed in parsed_pages.items():
        block = _tenant_block(parsed)
        for model, fields in block.items():
            agg = tenants.setdefault(model, {})
            for field, v in fields.items():
                agg[field] = agg.get(field, 0.0) + v
            if fields.get("device_seconds"):
                per_replica_device.setdefault(model, {})[replica] = \
                    round(fields["device_seconds"], 6)
        fam = parsed.get("dks_slo_budget_remaining")
        if fam:
            # ONE pass feeds both views: the per-SLO fleet minima and —
            # for templated tenant SLOs — the per-tenant minimum over
            # the tenant's objectives and the replicas
            for name, labels, value in fam["samples"]:
                slo = labels.get("slo")
                if not slo:
                    continue
                slo_budgets[slo] = min(
                    slo_budgets.get(slo, float("inf")), value)
                model = _tenant_of_slo(slo)
                if model is not None:
                    budgets[model] = min(budgets.get(model, float("inf")),
                                         value)
    for model, agg in tenants.items():
        for field, v in list(agg.items()):
            agg[field] = round(v, 6)
        agg["answered_ok"] = round(
            agg.get("requests", 0.0) - agg.get("errors", 0.0), 6)
        if model in budgets:
            agg["budget_remaining"] = round(budgets[model], 6)
        agg["per_replica_device_seconds"] = per_replica_device.get(model, {})
    top = sorted(tenants.items(),
                 key=lambda kv: -kv[1].get("device_seconds", 0.0))[:top_n]
    merged_exemplars: List[Dict] = []
    for replica, entries in (exemplars or {}).items():
        for e in entries:
            e = dict(e)
            e["replica"] = str(replica)
            merged_exemplars.append(e)
    merged_exemplars.sort(key=lambda e: -float(e.get("value", 0.0)))
    # the replica block covers every replica the sweep ATTEMPTED
    # (replica_meta), not just the ones that answered — an operator must
    # see scraped=false for the member missing from the sums
    replica_keys = (list(replica_meta) if replica_meta
                    else [str(r) for r in parsed_pages])
    return {
        "generated_at": time.time() if now is None else now,
        "replicas": {str(r): dict(replica_meta.get(str(r), {})
                                  if replica_meta else {})
                     for r in replica_keys},
        "tenants": tenants,
        "top_tenants_by_cost": [[model, agg.get("device_seconds", 0.0)]
                                for model, agg in top],
        "fleet": {
            "device_seconds": round(sum(
                a.get("device_seconds", 0.0) for a in tenants.values()), 6),
            "requests": round(sum(
                a.get("requests", 0.0) for a in tenants.values()), 6),
            "answered_ok": round(sum(
                a.get("answered_ok", 0.0) for a in tenants.values()), 6),
        },
        "slo_budget_remaining": {k: round(v, 6)
                                 for k, v in sorted(slo_budgets.items())},
        "exemplars": merged_exemplars[:64],
    }
