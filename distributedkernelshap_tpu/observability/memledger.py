"""Device-memory ledger: every owner of live device bytes opens an
account.

The fleet can attribute every device-*second* to a tenant (costmeter),
but device *bytes* had no observer: six content-fingerprint LRU device
caches (``_dev_cache``, linear plan consts, ``_exact_consts``,
``_exact_reach_full``, ``_exact_tn_consts``, ``_deepshap_consts``, the
anytime consts), the byte-budget result cache, staged batch buffers and
the anytime keep-best entries each bound themselves *locally*, so a
multi-tenant host discovered memory exhaustion by dying.  The
:class:`MemLedger` is the process-wide ledger those owners charge and
release against on every insert/evict, labeled ``{owner, model,
version, path}``, so "total live device bytes per tenant" becomes one
gauge (``dks_device_bytes{owner,model}``) next to the cost plane's
device-seconds.

Bytes are COMPUTED (sum of ``.nbytes`` over the charged value's array
leaves), not measured: the ledger never touches the device.  Where the
backend provides ``device.memory_stats()`` (TPU/GPU), :meth:`reconcile`
reports the gap between allocator truth and the ledger's computed total
(``dks_mem_reconcile_gap_bytes``); the CPU backend provides no
allocator stats, so there the ledger is computed-bytes-only by design
(the gap renders as 0 with ``supported: false`` in the ``/statusz``
memory panel).

**Pressure contract**: a configurable soft budget
(``DKS_MEM_BUDGET_BYTES`` / :meth:`set_budget`; 0 = unlimited).  A
charge that lifts the total above the budget emits ONE
``memory_pressure`` flight event and invokes the registered pressure
callbacks (result-cache byte eviction, LRU shrink of every tracked
device cache — largest account first) until the total is back under the
threshold or nothing more can be freed.  Eviction only ever forces
recompute: served answers stay bit-identical, because every evictable
buffer is a pure function of fingerprinted content.  A
:class:`TrackedCache` never evicts its most-recently-used entry, so the
engine's check-then-read lookup pattern cannot lose the entry it just
touched to a concurrent pressure sweep.

Stdlib-only (the observability package contract): array bytes are read
via duck-typed ``.nbytes``; ``jax`` is imported lazily inside
:meth:`reconcile` only.
"""

import logging
import os
import threading
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from distributedkernelshap_tpu.analysis.lockwitness import (
    make_lock,
    make_rlock,
)

logger = logging.getLogger(__name__)

#: label value rendered for charges that carry no model id (engines used
#: outside the registry) — mirrors the costmeter's default tenant
DEFAULT_MODEL_LABEL = "default"

#: bounded recursion when computing nbytes over nested containers
_NBYTES_MAX_DEPTH = 6


def resolve_mem_ledger_env(default: bool = True) -> bool:
    """``DKS_MEM_LEDGER=0`` disables the ledger (charges become no-ops;
    the metric families still register so the catalog is mode-
    independent, mirroring the costmeter's escape hatch)."""

    raw = os.environ.get("DKS_MEM_LEDGER")
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no")


def resolve_mem_budget_env(default: int = 0) -> int:
    """``DKS_MEM_BUDGET_BYTES`` — soft budget in bytes (0 = unlimited).
    Garbage parses as the default, loudly."""

    raw = os.environ.get("DKS_MEM_BUDGET_BYTES")
    if raw is None or raw.strip() == "":
        return default
    try:
        return max(0, int(float(raw.strip())))
    except ValueError:
        logger.warning("DKS_MEM_BUDGET_BYTES=%r is not a number; "
                       "using %d", raw, default)
        return default


def approx_nbytes(value, _depth: int = 0) -> int:
    """Computed bytes of ``value``: sum of ``.nbytes`` over every array
    leaf reachable through tuples/lists/dicts (numpy and jax arrays both
    expose ``.nbytes`` — no jax import needed).  Non-array scalars and
    opaque objects count 0; recursion is depth-bounded."""

    if value is None or _depth > _NBYTES_MAX_DEPTH:
        return 0
    n = getattr(value, "nbytes", None)
    if n is not None:
        try:
            return int(n)
        except (TypeError, ValueError):
            return 0
    if isinstance(value, dict):
        return sum(approx_nbytes(v, _depth + 1) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(approx_nbytes(v, _depth + 1) for v in value)
    if isinstance(value, (str, bytes)):
        return len(value)
    return 0


class Account:
    """One owner's view into the ledger: a labeled bag of per-key byte
    charges.  All bookkeeping happens under the ledger's lock; the
    account itself is just the label tuple plus its charge map."""

    __slots__ = ("ledger", "owner", "model", "version", "path",
                 "_charges", "_total", "__weakref__")

    def __init__(self, ledger: "MemLedger", owner: str,
                 model: Optional[str], version: Optional[int],
                 path: Optional[str]):
        self.ledger = ledger
        self.owner = owner
        self.model = model
        self.version = version
        self.path = path
        self._charges: Dict = {}
        self._total = 0

    def charge(self, key, nbytes: int, sweep: bool = True) -> None:
        """Record ``nbytes`` live bytes under ``key`` (replacing any
        prior charge for the key).  May trigger the pressure sweep —
        callers charging while holding their own container lock pass
        ``sweep=False`` and call :meth:`MemLedger.poke` after releasing
        it (the sweep re-enters containers to evict)."""

        self.ledger._charge(self, key, int(nbytes), sweep=sweep)

    def release(self, key) -> int:
        """Drop the charge for ``key``; returns the bytes released
        (0 when the key was never charged or was already retired)."""

        return self.ledger._release(self, key)

    def clear(self) -> int:
        """Release every charge; returns the bytes released."""

        return self.ledger._clear_account(self)

    @property
    def total_bytes(self) -> int:
        return self._total


class TrackedCache(OrderedDict):
    """An ``OrderedDict`` LRU that mirrors every mutation into ledger
    accounts — drop-in for the engine's device caches so the existing
    insert/evict sites (``cache[k] = v`` + ``popitem(last=False)``)
    charge and release without being touched.

    ``owner_for_key`` routes heterogenous caches (the plan-consts cache
    holds linear/exact/tensor-network/deepshap/anytime constants under
    distinct key shapes) to per-owner accounts.  ``rebind`` relabels the
    live charges when the registry later learns the tenant.  Mutations
    are serialized by an internal lock so a pressure sweep on another
    thread cannot interleave with the owning thread's insert."""

    def __init__(self, ledger: "MemLedger", owner: str,
                 nbytes_fn: Callable = approx_nbytes,
                 owner_for_key: Optional[Callable] = None,
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 path: Optional[str] = None):
        super().__init__()
        self._ledger = ledger
        self._owner = owner
        self._nbytes_fn = nbytes_fn
        self._owner_for_key = owner_for_key
        self._labels = {"model": model, "version": version, "path": path}
        # charge keys are namespaced by a per-cache token: two caches
        # sharing an account (same owner+model) must not collide on
        # equal cache keys
        self._token = object()
        # cache key -> (account, ledger charge key, nbytes)
        self._charged: Dict = {}
        # reentrant: OrderedDict.pop/popitem dispatch through the
        # subclass __delitem__, so evict_bytes nests the lock
        self._tc_lock = make_rlock("memledger.tracked_cache")
        ledger._track(self)
        # release this cache's live charges when the owning engine is
        # garbage collected (unregistered-tenant engines never get an
        # explicit retire); the finalizer must not strongly reference
        # the cache itself
        weakref.finalize(self, ledger._purge_charges, self._charged)

    # -- ledger plumbing ------------------------------------------------

    def _account_for(self, key) -> Account:
        owner = (self._owner_for_key(key) if self._owner_for_key
                 else self._owner)
        return self._ledger.account(owner, **self._labels)

    def _charge_key(self, key, value) -> None:
        if not self._ledger.enabled:
            return
        acct = self._account_for(key)
        n = int(self._nbytes_fn(value))
        ck = (self._token, key)
        self._charged[key] = (acct, ck, n)
        acct.charge(ck, n, sweep=False)

    def _release_key(self, key) -> None:
        entry = self._charged.pop(key, None)
        if entry is not None:
            entry[0].release(entry[1])

    @property
    def ledger_bytes(self) -> int:
        """This cache's own view of its live charged bytes."""

        with self._tc_lock:
            return sum(n for _, _, n in self._charged.values())

    def rebind(self, model: Optional[str] = None,
               version: Optional[int] = None,
               path: Optional[str] = None) -> None:
        """Relabel live charges (the registry calls this when a model
        built before registration gains its tenant identity)."""

        with self._tc_lock:
            self._labels = {"model": model, "version": version,
                            "path": path}
            for key in list(self._charged):
                acct, ck, n = self._charged[key]
                acct.release(ck)
                fresh = self._account_for(key)
                self._charged[key] = (fresh, ck, n)
                fresh.charge(ck, n, sweep=False)

    def evict_bytes(self, nbytes: int) -> int:
        """LRU-evict until at least ``nbytes`` are freed, but never the
        most-recently-used entry (see module doc).  Returns freed."""

        freed = 0
        with self._tc_lock:
            while len(self) > 1 and freed < nbytes:
                key = next(iter(self))
                entry = self._charged.get(key)
                n = entry[2] if entry is not None else 0
                # routes through __delitem__, releasing the charge
                OrderedDict.popitem(self, last=False)
                freed += n
        return freed

    # -- mutation overrides.  ``pop``/``popitem``/``del`` all dispatch
    # through ``__delitem__`` on an OrderedDict subclass; ``update`` and
    # ``setdefault`` through ``__setitem__``; only ``clear`` bypasses
    # both and needs its own wrapper. -----------------------------------

    def __setitem__(self, key, value):
        with self._tc_lock:
            self._release_key(key)
            OrderedDict.__setitem__(self, key, value)
            self._charge_key(key, value)
        # the pressure sweep re-enters tracked caches to evict, so it
        # must run with this cache's lock released
        self._ledger.poke()

    def __delitem__(self, key):
        with self._tc_lock:
            OrderedDict.__delitem__(self, key)
            self._release_key(key)

    def clear(self):
        with self._tc_lock:
            OrderedDict.clear(self)
            for key in list(self._charged):
                self._release_key(key)


class MemLedger:
    """Process-wide device-byte ledger (see module doc).  Thread-safe;
    all totals are integers of computed bytes."""

    def __init__(self, enabled: Optional[bool] = None,
                 budget_bytes: Optional[int] = None):
        self.enabled = (resolve_mem_ledger_env() if enabled is None
                        else bool(enabled))
        self._budget = (resolve_mem_budget_env() if budget_bytes is None
                        else max(0, int(budget_bytes)))
        self._lock = make_lock("memledger.accounts")
        self._accounts: Dict[Tuple, Account] = {}
        # id -> weakref (dict subclasses are unhashable, so no WeakSet)
        self._caches: Dict[int, weakref.ref] = {}
        self._pressure_cbs: List = []  # WeakMethod | callable
        self._total = 0
        self._high_water = 0
        self._pressure_events = 0
        self._evicted_bytes = 0
        self._last_gap: Optional[int] = None
        self._in_pressure = threading.local()

    # -- accounts -------------------------------------------------------

    def account(self, owner: str, model: Optional[str] = None,
                version: Optional[int] = None,
                path: Optional[str] = None) -> Account:
        """The (owner, model, version, path) account, created on first
        use.  Accounts are interned: same labels, same object."""

        key = (str(owner), model, version, path)
        with self._lock:
            acct = self._accounts.get(key)
            if acct is None:
                acct = self._accounts[key] = Account(
                    self, str(owner), model, version, path)
            return acct

    def tracked_cache(self, owner: str,
                      nbytes_fn: Callable = approx_nbytes,
                      owner_for_key: Optional[Callable] = None,
                      model: Optional[str] = None,
                      version: Optional[int] = None,
                      path: Optional[str] = None) -> TrackedCache:
        """A ledger-mirroring :class:`TrackedCache` enrolled in the
        pressure sweep."""

        return TrackedCache(self, owner, nbytes_fn=nbytes_fn,
                            owner_for_key=owner_for_key, model=model,
                            version=version, path=path)

    def _track(self, cache: TrackedCache) -> None:
        with self._lock:
            # opportunistic prune: dead refs leave with the next track
            # or pressure sweep (a GC-time callback could fire while
            # the ledger lock is held — not worth the deadlock risk)
            for token in [t for t, r in self._caches.items()
                          if r() is None]:
                self._caches.pop(token, None)
            self._caches[id(cache)] = weakref.ref(cache)

    # -- charge/release core -------------------------------------------

    def _charge(self, acct: Account, key, nbytes: int,
                sweep: bool = True) -> None:
        if not self.enabled:
            return
        nbytes = max(0, int(nbytes))
        with self._lock:
            old = acct._charges.pop(key, 0)
            acct._charges[key] = nbytes
            delta = nbytes - old
            acct._total += delta
            self._total += delta
            if self._total > self._high_water:
                self._high_water = self._total
            over = (self._total - self._budget) if self._budget else 0
        if sweep and over > 0:
            self._pressure(over)

    def poke(self) -> None:
        """Run the pressure sweep if over budget — for callers that
        charged with ``sweep=False`` under their own lock."""

        if not self.enabled or not self._budget:
            return
        over = self.overage_bytes()
        if over > 0:
            self._pressure(over)

    def _release(self, acct: Account, key) -> int:
        if not self.enabled:
            return 0
        with self._lock:
            n = acct._charges.pop(key, 0)
            acct._total -= n
            self._total -= n
            return n

    def _clear_account(self, acct: Account) -> int:
        if not self.enabled:
            return 0
        with self._lock:
            n = acct._total
            acct._charges.clear()
            acct._total = 0
            self._total -= n
            return n

    def _purge_charges(self, charged: Dict) -> None:
        """Finalizer for a dead :class:`TrackedCache`: release whatever
        it still had charged (best-effort — interpreter shutdown may
        have torn pieces down)."""

        try:
            for key, (acct, ck, _n) in list(charged.items()):
                acct.release(ck)
            charged.clear()
        except Exception:  # pragma: no cover - shutdown races
            return

    # -- budget & pressure ----------------------------------------------

    @property
    def budget_bytes(self) -> int:
        return self._budget

    def set_budget(self, nbytes: int) -> None:
        self._budget = max(0, int(nbytes))

    def register_pressure_callback(self, cb: Callable[[int], int]) -> None:
        """Register ``cb(overage_bytes) -> freed_bytes``.  Bound methods
        are held weakly (a stopped server's result cache must not be
        kept alive by the process ledger); plain callables strongly."""

        try:
            ref = weakref.WeakMethod(cb)
        except TypeError:
            ref = None
        with self._lock:
            self._pressure_cbs.append(ref if ref is not None else cb)

    def overage_bytes(self) -> int:
        with self._lock:
            return (self._total - self._budget) if self._budget else 0

    def _pressure(self, overage: int) -> None:
        """One pressure sweep: flight event, then callbacks, then LRU
        shrink of tracked caches (largest first) until under budget.
        Re-entrancy-guarded — callbacks charge/release themselves."""

        if getattr(self._in_pressure, "active", False):
            return
        self._in_pressure.active = True
        try:
            with self._lock:
                self._pressure_events += 1
                total, budget = self._total, self._budget
                cbs = list(self._pressure_cbs)
                caches = [r() for r in self._caches.values()]
            caches = [c for c in caches if c is not None]
            # largest account first; ledger_bytes takes each cache's own
            # lock, so the sort must happen outside the ledger lock
            caches.sort(key=lambda c: -c.ledger_bytes)
            try:
                from distributedkernelshap_tpu.observability.flightrec \
                    import flightrec
                flightrec().record("memory_pressure", total_bytes=total,
                                   budget_bytes=budget,
                                   overage_bytes=overage)
            except Exception:  # pragma: no cover - recorder must not
                pass           # break the charge path
            freed = 0
            for entry in cbs:
                fn = entry() if isinstance(entry, weakref.WeakMethod) \
                    else entry
                if fn is None:
                    continue
                over = self.overage_bytes()
                if over <= 0:
                    break
                try:
                    freed += max(0, int(fn(over) or 0))
                except Exception:
                    logger.exception("memory pressure callback failed")
            for cache in caches:
                over = self.overage_bytes()
                if over <= 0:
                    break
                freed += cache.evict_bytes(over)
            with self._lock:
                self._evicted_bytes += freed
                self._pressure_cbs = [
                    e for e in self._pressure_cbs
                    if not (isinstance(e, weakref.WeakMethod)
                            and e() is None)]
            if self.overage_bytes() > 0:
                logger.warning(
                    "memory pressure: still %d bytes over the %d-byte "
                    "budget after freeing %d (remaining owners hold "
                    "only their MRU entries)", self.overage_bytes(),
                    self._budget, freed)
        finally:
            self._in_pressure.active = False

    # -- views ----------------------------------------------------------

    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def high_water_bytes(self) -> int:
        with self._lock:
            return self._high_water

    def totals(self) -> Dict[Tuple[str, str], int]:
        """``{(owner, model_label): bytes}`` over non-empty accounts."""

        with self._lock:
            out: Dict[Tuple[str, str], int] = {}
            for acct in self._accounts.values():
                if not acct._total:
                    continue
                label = acct.model or DEFAULT_MODEL_LABEL
                k = (acct.owner, label)
                out[k] = out.get(k, 0) + acct._total
            return out

    def owner_totals(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for acct in self._accounts.values():
                if acct._total:
                    out[acct.owner] = out.get(acct.owner, 0) + acct._total
            return out

    def model_totals(self) -> Dict[str, int]:
        """Per-tenant live bytes (the /fleetz rollup's per-replica
        source, via the ``dks_device_bytes`` samples)."""

        with self._lock:
            out: Dict[str, int] = {}
            for acct in self._accounts.values():
                if acct._total:
                    label = acct.model or DEFAULT_MODEL_LABEL
                    out[label] = out.get(label, 0) + acct._total
            return out

    def retire(self, model_id: str, version: Optional[int] = None) -> int:
        """Drop every charge labeled with ``model_id`` (optionally one
        version) — called by the registry on unregister/hot-swap,
        mirroring the costmeter's label retirement.  The callback gauge
        stops rendering the tenant at the next scrape.  Returns the
        bytes dropped."""

        dropped = 0
        with self._lock:
            for acct in self._accounts.values():
                if acct.model != model_id:
                    continue
                if version is not None and acct.version != version:
                    continue
                dropped += acct._total
                acct._charges.clear()
                self._total -= acct._total
                acct._total = 0
        return dropped

    def pressure_events(self) -> int:
        with self._lock:
            return self._pressure_events

    def evicted_bytes(self) -> int:
        with self._lock:
            return self._evicted_bytes

    def reconcile(self) -> Dict:
        """Computed total vs the backend allocator, where the backend
        provides ``memory_stats()`` (TPU/GPU).  The CPU backend returns
        none — ``supported: false``, computed-bytes-only."""

        stats = None
        try:
            import jax

            devices = jax.local_devices()
            if devices:
                stats = devices[0].memory_stats()
        except Exception:
            stats = None
        ledger = self.total_bytes()
        if not stats or "bytes_in_use" not in stats:
            self._last_gap = None
            return {"supported": False, "ledger_bytes": ledger}
        gap = int(stats["bytes_in_use"]) - ledger
        self._last_gap = gap
        return {"supported": True, "ledger_bytes": ledger,
                "bytes_in_use": int(stats["bytes_in_use"]),
                "gap_bytes": gap}

    def snapshot(self) -> Dict:
        """The ``/statusz`` ``detail.memory`` panel."""

        with self._lock:
            owners = {}
            models = {}
            for acct in self._accounts.values():
                if not acct._total:
                    continue
                owners[acct.owner] = owners.get(acct.owner, 0) \
                    + acct._total
                label = acct.model or DEFAULT_MODEL_LABEL
                models[label] = models.get(label, 0) + acct._total
            doc = {
                "enabled": self.enabled,
                "total_bytes": self._total,
                "high_water_bytes": self._high_water,
                "budget_bytes": self._budget,
                "pressure_events": self._pressure_events,
                "evicted_bytes": self._evicted_bytes,
                "owners": owners,
                "models": models,
            }
        doc["reconcile"] = self.reconcile()
        return doc

    def reset(self) -> None:
        """Zero every account and counter (bench/test hook: lets one
        process measure a fresh ledger epoch; live TrackedCaches keep
        working — their stale charge entries release as 0)."""

        with self._lock:
            for acct in self._accounts.values():
                acct._charges.clear()
                acct._total = 0
            self._total = 0
            self._high_water = 0
            self._pressure_events = 0
            self._evicted_bytes = 0
            self._last_gap = None

    # -- metrics --------------------------------------------------------

    def attach_metrics(self, registry) -> None:
        """Register the ledger's families on ``registry`` (callback-
        sourced; several registries may read one process ledger).  The
        model-labeled gauge declares a retire hook — :meth:`retire` runs
        on tenant removal/hot-swap, so churn cannot grow the label
        space."""

        g = registry.gauge(
            "dks_device_bytes",
            "Live device bytes by owning buffer and tenant — computed "
            "nbytes charged to the process memory ledger on every cache "
            "insert/evict (engine device caches, plan constants, result "
            "cache, staging slots, anytime constants).  Retired with "
            "the tenant on unregister/hot-swap.",
            labelnames=("owner", "model"))
        g.set_function(lambda: {k: float(v)
                                for k, v in self.totals().items()})
        registry.declare_retirement("dks_device_bytes")
        registry.gauge(
            "dks_mem_high_water_bytes",
            "High-water mark of the memory ledger's total computed "
            "device bytes since process start (or the last ledger "
            "reset).").set_function(
                lambda: float(self.high_water_bytes()))
        registry.gauge(
            "dks_mem_budget_bytes",
            "Configured soft device-byte budget (DKS_MEM_BUDGET_BYTES; "
            "0 = unlimited).  Charges above it trigger the pressure "
            "sweep.").set_function(lambda: float(self._budget))
        registry.counter(
            "dks_mem_pressure_events_total",
            "Memory-pressure sweeps triggered (total charged bytes "
            "exceeded the soft budget; each sweep also lands a "
            "memory_pressure flight event).").set_function(
                lambda: float(self.pressure_events()))
        registry.counter(
            "dks_mem_evicted_bytes_total",
            "Bytes freed by pressure sweeps (result-cache eviction + "
            "LRU shrink of tracked device caches).  Eviction only "
            "forces recompute — answers stay bit-identical.").\
            set_function(lambda: float(self.evicted_bytes()))
        registry.gauge(
            "dks_mem_reconcile_gap_bytes",
            "Last reconciliation gap: backend allocator bytes_in_use "
            "minus the ledger's computed total.  0 when the backend "
            "exposes no memory_stats (CPU) — the /statusz memory panel "
            "carries the supported flag.").set_function(
                lambda: float(self._last_gap or 0))


_default: Optional[MemLedger] = None
_default_lock = make_lock("memledger.singleton")


def memledger() -> MemLedger:
    """The process-wide ledger (created on first use, honoring the
    ``DKS_MEM_LEDGER`` / ``DKS_MEM_BUDGET_BYTES`` environment)."""

    global _default
    with _default_lock:
        if _default is None:
            _default = MemLedger()
        return _default
